"""Rule ``env-contract``: every ``KFAC_*``/``JAX_*`` knob is declared.

The env surface grew organically across fourteen PRs (~190 read sites)
with three partial validators — ``faults.from_env`` STRICT mode,
``launch_tpu.sh``'s case blocks, the README table — each hand-kept and
each incomplete: a typo'd ``KFAC_COMM_PRECISON=bf16`` exported next to
a trainer silently did nothing. ``kfac_pytorch_tpu/envspec.py`` is now
the single registry (pure literal data, so this rule reads it without
importing anything), and those validators derive from it.

This rule closes the loop at review time:

- any **full-string literal** matching ``^(KFAC|JAX)_[A-Z0-9_]*[A-Z0-9]$``
  anywhere in the shipped tree (an ``os.environ`` read, an ``ENV_FOO =``
  constant, a child-env re-export list, a spec allowlist) must name a
  declared variable — an undeclared name is either a typo or an
  undocumented knob, both lint errors;
- an ``os.environ``/``os.getenv`` read whose *name argument is built
  dynamically* (f-string, concatenation, call) is flagged: dynamic
  names defeat the registry, so they need an explicit per-site
  suppression with a reason.

Prefix scans (``k.startswith('KFAC_FAULT_')``) use trailing-underscore
literals, which the pattern deliberately does not match.
"""

import ast
import re
from typing import List

from kfac_pytorch_tpu.analysis import astutil
from kfac_pytorch_tpu.analysis.core import Finding, ModuleInfo, \
    RepoContext, Rule

ENVSPEC = 'kfac_pytorch_tpu/envspec.py'

ENV_NAME_RE = re.compile(r'^(KFAC|JAX)_[A-Z0-9_]*[A-Z0-9]$')

#: receivers that make a ``.get``/``.pop``/``.setdefault``/``[]``/
#: ``in`` an environment read
_ENVIRON_HEADS = ('os.environ', 'environ')
_READ_METHODS = ('get', 'pop', 'setdefault')


def _is_environ(node: ast.AST) -> bool:
    d = astutil.dotted(node)
    return d is not None and (d in _ENVIRON_HEADS
                              or d.endswith('.environ'))


class EnvContractRule(Rule):
    id = 'env-contract'
    summary = 'every KFAC_*/JAX_* env name is declared in envspec.py'
    invariant = ('central env contract: envspec.ENV declares every '
                 'knob; faults.from_env STRICT validation, '
                 'launch_tpu.sh and the README table derive from it')
    caught = ('undeclared/typo\'d KFAC_* knobs that silently never '
              'armed (multiple PRs\' review rounds)')

    def scope(self, relpath: str) -> bool:
        return relpath != ENVSPEC \
            and not relpath.startswith('kfac_pytorch_tpu/analysis/')

    def declared(self, ctx: RepoContext) -> frozenset:
        """Statically lift the declared names out of envspec.py: every
        ``E('NAME', ...)`` call with a literal first argument."""
        mod = ctx.module(ENVSPEC)
        names = set()
        if mod.tree is None:              # pragma: no cover - repo parses
            return frozenset()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ('E', 'EnvVar') and node.args:
                name = astutil.str_const(node.args[0])
                if name:
                    names.add(name)
        return frozenset(names)

    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        declared = self.declared(ctx)
        doc_lines = astutil.docstring_linenos(mod.tree)
        # strings inside __all__ are exported Python symbols, not env
        # names, even when the symbol happens to look like one
        all_lines = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == '__all__'
                    for t in node.targets):
                for ln in range(node.lineno, (node.end_lineno
                                              or node.lineno) + 1):
                    all_lines.add(ln)
        doc_lines |= all_lines
        out = []
        for node in ast.walk(mod.tree):
            # (a) any env-shaped full-string literal must be declared
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.lineno not in doc_lines \
                    and ENV_NAME_RE.match(node.value) \
                    and node.value not in declared:
                out.append(Finding(
                    self.id, mod.relpath, node.lineno,
                    f'{node.value!r} is not declared in envspec.ENV — '
                    f'declare it (name, kind, consumer, doc) or fix '
                    f'the typo', node.col_offset))
            # (b) dynamic env names defeat the registry
            elif isinstance(node, ast.Call):
                name_arg = None
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _READ_METHODS \
                        and _is_environ(f.value) and node.args:
                    name_arg = node.args[0]
                elif astutil.dotted(f) in ('os.getenv', 'getenv') \
                        and node.args:
                    name_arg = node.args[0]
                if name_arg is not None and not (
                        astutil.str_const(name_arg) is not None
                        or isinstance(name_arg, ast.Name)
                        or (isinstance(name_arg, ast.Attribute))):
                    out.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        'environment read with a dynamically-built '
                        'name — the envspec registry cannot see it; '
                        'use a declared literal/constant or suppress '
                        'with a reason', node.col_offset))
            elif isinstance(node, ast.Subscript) and _is_environ(node.value):
                sl = node.slice
                if astutil.str_const(sl) is None \
                        and not isinstance(sl, (ast.Name, ast.Attribute)):
                    out.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        'os.environ[...] with a dynamically-built name '
                        '— use a declared literal/constant or suppress '
                        'with a reason', node.col_offset))
        return out
