"""Rule ``knob-writer``: only the arbiter assigns runtime knobs.

PR 9's costliest review-round bug: three controllers (the param
scheduler, the straggler governor, the elastic rescale hook) raced
last-writer-wins over the same ``KFAC`` attributes. The fix made
``autotune.KnobArbiter`` the single writer of ``KNOB_ATTRS`` and
demoted everyone else to proposers — enforced at runtime by a
``__setattr__``-guard test (tests/test_autotune.py). This rule is the
static half: an *assignment* to a knob attribute (or a ``setattr``
with a literal knob name) anywhere outside the arbiter module is a
violation the reviewer sees before the drill runs.

Allowed, by construction of the discipline itself:

- ``kfac_pytorch_tpu/autotune.py`` — the arbiter (whole module);
- any ``__init__``/``__post_init__`` — construction-time base values
  are the arbiter's *input*, not a runtime write;
- ``KFAC.replan`` in preconditioner.py — the live-replanning commit
  writes ``comm_mode`` under the arbiter's ``_applying()`` guard (the
  runtime test proves the guard is actually held there).

``KNOB_ATTRS`` is read statically out of autotune.py, so a knob added
there is instantly law here too.
"""

from typing import List

import ast

from kfac_pytorch_tpu.analysis import astutil
from kfac_pytorch_tpu.analysis.core import Finding, ModuleInfo, \
    RepoContext, Rule

AUTOTUNE = 'kfac_pytorch_tpu/autotune.py'

#: (module, enclosing function) sites allowed to write a knob outside
#: __init__ — each must hold the arbiter's ``_applying()`` guard, which
#: the runtime setattr-guard test (tests/test_autotune.py) verifies
ALLOWED_SITES = frozenset({
    ('kfac_pytorch_tpu/preconditioner.py', 'replan'),
})

_CONSTRUCTORS = ('__init__', '__post_init__', '__new__')


def _assigned_attrs(target):
    """The Attribute nodes a target actually REBINDS — not attribute
    reads inside subscript slices (``table[cfg.damping] = 1`` reads the
    knob, it doesn't write it) and not subscripted containers
    (``x.buckets[0] = v`` mutates contents, not a knob binding)."""
    if isinstance(target, ast.Attribute):
        yield target
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _assigned_attrs(el)
    elif isinstance(target, ast.Starred):
        yield from _assigned_attrs(target.value)


class KnobWriterRule(Rule):
    id = 'knob-writer'
    summary = 'only autotune.KnobArbiter assigns KNOB_ATTRS at runtime'
    invariant = ('single-writer knob arbitration: every runtime change '
                 'to fac/kfac_update_freq, damping, comm_precision, '
                 'decomp_impl, comm_mode flows through the arbiter')
    caught = ('PR 9: scheduler/governor/elastic racing last-writer-wins '
              'over the same KFAC attributes')

    def scope(self, relpath: str) -> bool:
        return relpath != AUTOTUNE and relpath.endswith('.py') \
            and not relpath.startswith('kfac_pytorch_tpu/analysis/')

    def _knobs(self, ctx: RepoContext):
        return tuple(ctx.static_literal(AUTOTUNE, 'KNOB_ATTRS'))

    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        knobs = set(self._knobs(ctx))
        out = []

        def flag(node, attr):
            out.append(Finding(
                self.id, mod.relpath, node.lineno,
                f'direct write to knob attribute {attr!r} — runtime '
                f'knob changes must go through autotune.KnobArbiter '
                f'(propose/commit), not assignment', node.col_offset))

        for node, func in astutil.walk_with_func(mod.tree):
            if func in _CONSTRUCTORS:
                continue
            if (mod.relpath, func) in ALLOWED_SITES:
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for el in _assigned_attrs(t):
                        if el.attr in knobs:
                            flag(node, el.attr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == 'setattr' and len(node.args) >= 2:
                name = astutil.str_const(node.args[1])
                if name in knobs:
                    flag(node, name)
        return out
