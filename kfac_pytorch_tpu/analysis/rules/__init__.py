"""Rule registry: the six project invariants ``kfac-lint`` enforces.

Each rule module defines one :class:`~kfac_pytorch_tpu.analysis.core.
Rule` subclass; ``ALL_RULES`` is the ordered registry the CLI and the
tests iterate. Adding a rule = adding a module here + a fixture pair in
``tests/test_lint.py`` (one snippet it catches, one it passes) + a row
in the README table.
"""

from kfac_pytorch_tpu.analysis.rules.knob_writer import KnobWriterRule
from kfac_pytorch_tpu.analysis.rules.coord_bypass import CoordBypassRule
from kfac_pytorch_tpu.analysis.rules.env_contract import EnvContractRule
from kfac_pytorch_tpu.analysis.rules.event_grammar import EventGrammarRule
from kfac_pytorch_tpu.analysis.rules.atomic_write import AtomicWriteRule
from kfac_pytorch_tpu.analysis.rules.trace_purity import TracePurityRule

ALL_RULES = (
    KnobWriterRule(),
    CoordBypassRule(),
    EnvContractRule(),
    EventGrammarRule(),
    AtomicWriteRule(),
    TracePurityRule(),
)

RULE_IDS = tuple(r.id for r in ALL_RULES)
