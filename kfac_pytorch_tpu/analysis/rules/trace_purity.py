"""Rule ``trace-purity``: code reachable under jit/shard_map stays pure.

A traced body that calls ``time.*``, unseeded ``random``/``np.random``,
``print``, reads ``os.environ`` or mutates a module global doesn't
fail — it silently bakes one trace-time value into the compiled
program (or spams every retrace), which is exactly the class of bug
that cost a review round when a health-guard helper once logged from
inside the traced step. The runtime has no guard for this; the trace
is the only witness. This rule makes it a review-time fact.

Traced set, computed statically:

- **seed**: every function in ``TRACED_MODULES`` (engine.py and
  health.py are traced-library modules by charter — their docstrings
  say "pure and traceable" and the step builder calls them under
  shard_map), plus any function the tree passes to / decorates with
  ``jax.jit`` / ``shard_map`` / ``pjit`` / ``jax.remat`` /
  ``jax.checkpoint``;
- **propagation**: a function called *by* a traced function is traced
  too — resolved by name within the module and through the module's
  import table across the package, to a fixpoint.

Host-side escape hatches (``jax.debug.*``, ``jax.pure_callback``,
``io_callback``) are naturally exempt: the callback fn is passed as a
value, not called, so propagation never enters it.
"""

import ast
import os
from typing import Dict, List, Set, Tuple

from kfac_pytorch_tpu.analysis import astutil
from kfac_pytorch_tpu.analysis.core import Finding, ModuleInfo, \
    RepoContext, Rule

#: modules whose every function is traced-context by charter
TRACED_MODULES = (
    'kfac_pytorch_tpu/engine.py',
    'kfac_pytorch_tpu/health.py',
)

_WRAPPERS = ('jit', 'shard_map', 'pjit', 'remat', 'checkpoint')

_PKG = 'kfac_pytorch_tpu'


def _is_wrapper(func_node: ast.AST) -> bool:
    d = astutil.dotted(func_node)
    if d is None:
        return False
    last = d.split('.')[-1]
    return last in _WRAPPERS and (d == last or d.startswith('jax.')
                                  or d.startswith('compat.')
                                  or d.endswith('.' + last))


class _ModuleGraph:
    """Per-module function table + import table + call edges."""

    def __init__(self, relpath: str, mod: ModuleInfo, known: Set[str]):
        self.relpath = relpath
        self.funcs: Dict[str, ast.AST] = dict(astutil.func_defs(mod.tree))
        # simple-name -> qualnames defined in this module
        self.by_name: Dict[str, List[str]] = {}
        for qual in self.funcs:
            self.by_name.setdefault(qual.split('.')[-1], []).append(qual)
        self.imports = self._imports(mod.tree, known)

    def _imports(self, tree: ast.AST, known: Set[str]) -> Dict[str, str]:
        """alias -> package-relative module path ('a/b.py'), or
        'a/b.py::name' for a from-import of a single function."""
        out: Dict[str, str] = {}

        def rel_of(modname: str):
            if not modname.startswith(_PKG):
                return None
            p = modname.replace('.', '/') + '.py'
            if p in known:
                return p
            p = modname.replace('.', '/') + '/__init__.py'
            return p if p in known else None

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = rel_of(a.name)
                    if rel and a.asname:
                        out[a.asname] = rel
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self.relpath
                    for _ in range(node.level):
                        base = os.path.dirname(base)
                    modname = (base.replace('/', '.')
                               + ('.' + node.module if node.module else ''))
                else:
                    modname = node.module or ''
                if not modname.startswith(_PKG):
                    continue
                for a in node.names:
                    # 'from pkg import engine' binds the module itself;
                    # 'from pkg.engine import f' binds one name from it
                    alias = a.asname or a.name
                    sub = rel_of(modname + '.' + a.name)
                    if sub:
                        out[alias] = sub
                    else:
                        here = rel_of(modname)
                        if here:
                            out[alias] = here + '::' + a.name
        return out


class TracePurityRule(Rule):
    id = 'trace-purity'
    summary = 'jit/shard_map-reachable code: no time/random/print/env/global'
    invariant = ('trace purity: functions reachable under jit/shard_map '
                 'never call time.*, unseeded random/np.random, print, '
                 'read os.environ or mutate module globals')
    caught = ('trace-time values silently baked into compiled programs '
              '(PR 1/4 review rounds on the health guard and cohort '
              'tables)')

    def scope(self, relpath: str) -> bool:
        return relpath.startswith('kfac_pytorch_tpu/') \
            and not relpath.startswith('kfac_pytorch_tpu/analysis/')

    # ------------------------------------------------------------------
    def _state(self, ctx: RepoContext) -> Dict[str, List[Finding]]:
        cached = getattr(ctx, '_trace_purity_findings', None)
        if cached is not None:
            return cached
        rels = [r for r in self._package_files(ctx.root)
                if self.scope(r)]
        known = set(self._package_files(ctx.root))
        graphs: Dict[str, _ModuleGraph] = {}
        for rel in rels:
            mod = ctx.module(rel)
            if mod.tree is not None:
                graphs[rel] = _ModuleGraph(rel, mod, known)

        traced: Set[Tuple[str, str]] = set()
        for rel in TRACED_MODULES:
            g = graphs.get(rel)
            if g:
                traced |= {(rel, q) for q in g.funcs}

        # wrapper-detected seeds: decorators and jit(f)/shard_map(f, ..)
        for rel, g in graphs.items():
            mod = ctx.module(rel)
            for qual, fn in g.funcs.items():
                for dec in getattr(fn, 'decorator_list', []):
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _is_wrapper(target):
                        traced.add((rel, qual))
            # `fn = functools.partial(one_step, ...)` then `jit(fn)`:
            # follow the partial alias to the real body
            partial_alias: Dict[str, str] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and astutil.dotted(node.value.func) in (
                            'functools.partial', 'partial') \
                        and node.value.args \
                        and isinstance(node.value.args[0], ast.Name):
                    partial_alias[node.targets[0].id] = \
                        node.value.args[0].id
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and _is_wrapper(node.func) \
                        and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        name = partial_alias.get(arg.id, arg.id)
                        for q in g.by_name.get(name, []):
                            traced.add((rel, q))

        # propagate through call edges to a fixpoint
        edges = self._call_edges(graphs)
        work = list(traced)
        while work:
            cur = work.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in traced:
                    traced.add(nxt)
                    work.append(nxt)

        findings: Dict[str, List[Finding]] = {}
        for rel, qual in sorted(traced):
            g = graphs[rel]
            fn = g.funcs[qual]
            for f in self._check_body(rel, qual, fn):
                findings.setdefault(rel, []).append(f)
        ctx._trace_purity_findings = findings
        return findings

    def _package_files(self, root: str) -> List[str]:
        out = []
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, _PKG)):
            dirnames[:] = [d for d in dirnames if d != '__pycache__']
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, '/'))
        return sorted(out)

    def _call_edges(self, graphs: Dict[str, _ModuleGraph]):
        edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for rel, g in graphs.items():
            for qual, fn in g.funcs.items():
                tgt = edges.setdefault((rel, qual), set())
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Name):
                        imp = g.imports.get(f.id)
                        if imp and '::' in imp:
                            orel, oname = imp.split('::')
                            og = graphs.get(orel)
                            if og:
                                for q in og.by_name.get(oname, []):
                                    tgt.add((orel, q))
                        else:
                            for q in g.by_name.get(f.id, []):
                                tgt.add((rel, q))
                    elif isinstance(f, ast.Attribute):
                        base = astutil.dotted(f.value)
                        if base == 'self' or base is None:
                            for q in g.by_name.get(f.attr, []):
                                tgt.add((rel, q))
                        else:
                            imp = g.imports.get(base)
                            if imp and '::' not in imp:
                                og = graphs.get(imp)
                                if og:
                                    for q in og.by_name.get(f.attr, []):
                                        tgt.add((imp, q))
        return edges

    def _check_body(self, rel: str, qual: str, fn: ast.AST
                    ) -> List[Finding]:
        out = []

        def flag(node, what):
            out.append(Finding(
                self.id, rel, node.lineno,
                f'{qual}() is reachable under jit/shard_map but {what} '
                f'— a trace-time value/effect bakes into the compiled '
                f'program; hoist it to the host side or suppress with '
                f'a reason', node.col_offset))

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = astutil.dotted(node.func)
                if d is None:
                    continue
                if d.startswith('time.'):
                    flag(node, f'calls {d}()')
                elif d == 'print':
                    flag(node, 'calls print()')
                elif d == 'open':
                    flag(node, 'calls open()')
                elif d.startswith('random.') \
                        or d.startswith('np.random.') \
                        or d.startswith('numpy.random.'):
                    flag(node, f'calls unseeded {d}()')
            elif isinstance(node, ast.Attribute):
                if astutil.dotted(node) == 'os.environ':
                    flag(node, 'reads os.environ')
            elif isinstance(node, ast.Global):
                flag(node, f'mutates module global(s) '
                           f'{", ".join(node.names)}')
        return out

    # ------------------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        return self._state(ctx).get(mod.relpath, [])
