"""Rule ``coord-bypass``: protocol modules don't reach around the
coordination backend.

PR 12 routed every fleet protocol — shrink/grow claims, lineage,
heartbeat leases, join/done markers, queue epoch-CAS, the hosts.json
pool — through ``kfac_pytorch_tpu/coord``'s ``CoordBackend`` so the
whole fleet can move from the POSIX lease dir to a KV service by
flipping ``KFAC_COORD_BACKEND``. The abstraction rots the day one
protocol module quietly goes back to ``os.listdir``/``open`` on the
lease dir (exactly how the torn-JSON reader bugs of PR 7 happened).

This rule is the framework home of the ad-hoc AST scan that shipped
inside tests/test_coord.py: the protocol modules listed in
``PROTOCOL_MODULES`` may not call direct-filesystem primitives
(``os.listdir``/``os.replace``/``os.remove``/``os.rename``/
``shutil.rmtree``/``open``/``atomic_write_json``) outside the
per-module ``ALLOWED_FUNCS`` allowlist — each allowlisted function is
a named *artifact* writer/reader (incident reports, per-rank log
files, CLI spec input, the tuner's adopted-knobs snapshot), never
protocol state. Extending the allowlist means editing THIS file, in
review — which is the point. tests/test_coord.py now invokes this rule
(one source of truth; the test is a thin ``kfac-lint --rule
coord-bypass`` run).
"""

from typing import List

import ast

from kfac_pytorch_tpu.analysis import astutil
from kfac_pytorch_tpu.analysis.core import Finding, ModuleInfo, \
    RepoContext, Rule

#: direct-filesystem calls that USED to implement the protocols; any
#: new occurrence outside the allowlist is the abstraction rotting
FORBIDDEN = frozenset({
    ('os', 'listdir'), ('os', 'replace'), ('os', 'remove'),
    ('os', 'rename'), ('shutil', 'rmtree'), (None, 'open'),
    (None, 'atomic_write_json'),
})

#: protocol module -> {function names allowed to touch files directly}.
#: Every entry is a genuine ARTIFACT path (reviewed when added here):
#:   elastic.run            — per-host run log + incident report files
#:   scheduler._admit/main  — CLI spec input + per-job log plumbing
#:   scheduler._adopted_knobs — reads the tuner's adopted-knobs.json
#:                            snapshot out of the job's trace namespace
#: A module under coord/ itself is the backend, not a bypass, and is
#: deliberately NOT in scope.
PROTOCOL_MODULES = {
    'kfac_pytorch_tpu/resilience/elastic.py': frozenset({'run'}),
    'kfac_pytorch_tpu/resilience/heartbeat.py': frozenset(),
    'kfac_pytorch_tpu/service/queue.py': frozenset(),
    'kfac_pytorch_tpu/service/scheduler.py': frozenset({
        '_admit', 'main', '_adopted_knobs'}),
}


class CoordBypassRule(Rule):
    id = 'coord-bypass'
    summary = 'protocol modules route all shared state through CoordBackend'
    invariant = ('coord no-bypass: shrink/grow claims, leases, queue '
                 'epochs and the host pool live behind CoordBackend '
                 'primitives, never behind direct lease-dir file IO')
    caught = ('PR 7/12: torn-JSON protocol readers and non-atomic '
              'claim writes that only surfaced mid-drill')

    def scope(self, relpath: str) -> bool:
        return relpath in PROTOCOL_MODULES

    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        allowed = PROTOCOL_MODULES[mod.relpath]
        out = []
        for node, func in astutil.walk_with_func(mod.tree):
            if not isinstance(node, ast.Call) or func in allowed:
                continue
            name = modname = None
            f = node.func
            if isinstance(f, ast.Name):
                name = f.id
            elif isinstance(f, ast.Attribute):
                name = f.attr
                if isinstance(f.value, ast.Name):
                    modname = f.value.id
            for fmod, fname in FORBIDDEN:
                if name == fname and (fmod is None or modname == fmod):
                    call = f'{modname}.{name}' if modname else name
                    out.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        f'{func}() calls {call} — protocol state goes '
                        f'through the CoordBackend; if this is a genuine '
                        f'artifact, allowlist it in '
                        f'analysis/rules/coord_bypass.py (in review)',
                        node.col_offset))
        return out
