"""Rule ``event-grammar``: emitted event log forms parse under the
shared incident grammar.

``resilience/incident.py``'s ``EVENT_PATTERNS`` is the contract three
consumers share: incident scraping, the ``kfac-obs`` pod timeline, and
every CI drill that greps a run log for an event. The producers are
plain ``log.info(...)`` calls scattered across elastic/heartbeat/
supervisor/coord/autotune/service — nothing ties an emit site to its
regex, so grammar drift (reworded literal text, a renamed ``k=v``
field, a new field the regex can't see) historically surfaced
mid-drill as an empty timeline.

This rule ties them statically. For every static string template in
the tree (a %-style logging template, an f-string, a returned message
form), it synthesizes a sample line by substituting placeholders, then:

- the sample *claims* every pattern whose literal head it starts with
  (heads are computed from the regex sources, also statically);
- a claiming site must ``search``-match at least one claimed pattern
  *relaxed* — every named capture group loosened to ``.+?`` so only
  the literal skeleton is compared (the capture classes stay a runtime
  concern; the literal text IS the grammar).

A site that claims a head but matches no skeleton is drift. A
prefixed narration line that is deliberately *not* an event gets a
``# kfac-lint: disable=event-grammar -- <reason>`` at the site, which
is exactly the review conversation the grammar needs.
"""

import ast
import re
from typing import List, Optional, Tuple

from kfac_pytorch_tpu.analysis import astutil
from kfac_pytorch_tpu.analysis.core import Finding, ModuleInfo, \
    RepoContext, Rule

INCIDENT = 'kfac_pytorch_tpu/resilience/incident.py'

#: grammar definition + its two regex consumers: their files quote the
#: pattern sources themselves, which are not emit sites
EXCLUDED = (INCIDENT, 'kfac_pytorch_tpu/obs/aggregate.py')

#: a head must be at least this long to claim a site — short module
#: prefixes like ``elastic: `` alone prove nothing
MIN_HEAD = 12

_PCT = re.compile(r'%[-+ #0]*\d*(?:\.\d+)?([srdifFeEgGxXc%])')

_SAMPLES = {'s': 'x7', 'r': "'x7'", 'd': '7', 'i': '7', 'f': '3.5',
            'F': '3.5', 'e': '3.5', 'E': '3.5', 'g': '3.5', 'G': '3.5',
            'x': '7', 'X': '7', 'c': 'x', '%': '%'}

_META = set('([{.*+?|^$')


def _literal_head(src: str) -> str:
    """Leading literal text of a regex source (regex escapes resolved,
    stop at the first group/class/quantifier)."""
    out: List[str] = []
    i = 0
    while i < len(src):
        c = src[i]
        if c == '\\':
            nxt = src[i + 1] if i + 1 < len(src) else ''
            if nxt and nxt in '()[]{}.*+?|^$\\':
                out.append(nxt)
                i += 2
                continue
            break                       # \d, \S, \w... — a class
        if c in _META:
            if c in '*+?{' and out:     # quantifier on the last literal
                out.pop()
            break
        out.append(c)
        i += 1
    return ''.join(out)


def _skip_class(src: str, i: int) -> int:
    """``i`` points at '['; return index past the closing ']'."""
    j = i + 1
    if j < len(src) and src[j] == '^':
        j += 1
    if j < len(src) and src[j] == ']':
        j += 1
    while j < len(src) and src[j] != ']':
        j += 2 if src[j] == '\\' else 1
    return j + 1


def _relax(src: str) -> str:
    """Replace every named capture group's content with ``.+?`` so the
    literal skeleton is what gets matched."""
    out: List[str] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == '\\' and i + 1 < n:
            out.append(src[i:i + 2])
            i += 2
            continue
        if c == '[':
            j = _skip_class(src, i)
            out.append(src[i:j])
            i = j
            continue
        if src.startswith('(?P<', i):
            depth, j = 0, i
            while j < n:
                cj = src[j]
                if cj == '\\':
                    j += 2
                    continue
                if cj == '[':
                    j = _skip_class(src, j)
                    continue
                if cj == '(':
                    depth += 1
                elif cj == ')':
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            name_end = src.index('>', i)
            out.append(src[i:name_end + 1] + '.+?)')
            i = j + 1
            continue
        out.append(c)
        i += 1
    return ''.join(out)


def template_sample(node: ast.AST) -> Optional[Tuple[List[str], str]]:
    """(sample_texts, literal_prefix) for a static string template, or
    None. %-placeholders and f-string fields become sample values; the
    string-valued ones (``%s``, f-fields) are *also* tried as empty,
    because emit sites pass optional suffixes (`` at step N``, a
    resilience suffix) through a trailing ``%s`` that is legitimately
    absent from the grammar form."""
    s = astutil.str_const(node)
    if s is not None:
        full = _PCT.sub(lambda m: _SAMPLES[m.group(1)], s)
        bare = _PCT.sub(
            lambda m: '' if m.group(1) in 'sr' else _SAMPLES[m.group(1)], s)
        first = _PCT.search(s)
        prefix = s[:first.start()] if first else s
        return [full, bare], prefix
    if isinstance(node, ast.JoinedStr):
        full: List[str] = []
        bare: List[str] = []
        prefix: List[str] = []
        literal_so_far = True
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                full.append(v.value)
                bare.append(v.value)
                if literal_so_far:
                    prefix.append(v.value)
            else:
                full.append('7')
                literal_so_far = False
        return [''.join(full), ''.join(bare)], ''.join(prefix)
    return None


class EventGrammarRule(Rule):
    id = 'event-grammar'
    summary = 'emitted event log forms parse under incident.EVENT_PATTERNS'
    invariant = ('shared event grammar: every event-form emit site '
                 'search-matches some EVENT_PATTERNS regex, so '
                 'incident scraping / kfac-obs timelines never drift '
                 'from the producers')
    caught = ('grammar drift that emptied kfac-obs timelines and only '
              'surfaced mid-drill (PR 7/10 review rounds)')

    def scope(self, relpath: str) -> bool:
        return relpath.startswith('kfac_pytorch_tpu/') \
            and relpath not in EXCLUDED \
            and not relpath.startswith('kfac_pytorch_tpu/analysis/')

    def patterns(self, ctx: RepoContext):
        """Statically lift ``(kind, source, head, relaxed)`` out of
        incident.py's ``_PATTERNS`` tuple."""
        cached = getattr(ctx, '_event_patterns', None)
        if cached is not None:
            return cached
        tree = ctx.module(INCIDENT).tree
        pats = []
        for node in tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == '_PATTERNS'
                            for t in node.targets)):
                continue
            for el in node.value.elts:
                if not (isinstance(el, ast.Tuple) and len(el.elts) == 2):
                    continue
                kind = astutil.str_const(el.elts[0])
                call = el.elts[1]
                if not (isinstance(call, ast.Call) and call.args):
                    continue
                src = astutil.str_const(call.args[0])
                if kind and src:
                    head = _literal_head(src)
                    pats.append((kind, src, head,
                                 re.compile(_relax(src))))
        ctx._event_patterns = tuple(pats)
        return ctx._event_patterns

    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        pats = self.patterns(ctx)
        doc_lines = astutil.docstring_linenos(mod.tree)
        # an f-string's literal chunks are Constants too — only the
        # whole JoinedStr is the template, never its pieces
        nested = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.JoinedStr):
                for sub in ast.walk(node):
                    if sub is not node:
                        nested.add(id(sub))
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Constant, ast.JoinedStr)):
                continue
            if id(node) in nested or node.lineno in doc_lines:
                continue
            got = template_sample(node)
            if got is None:
                continue
            samples, prefix = got
            claimed = [(kind, relaxed) for kind, _src, head, relaxed
                       in pats
                       if len(head) >= MIN_HEAD
                       and (prefix.startswith(head)
                            or (len(prefix) >= MIN_HEAD
                                and head.startswith(prefix)))]
            if not claimed:
                continue
            if any(r.search(s) for _k, r in claimed for s in samples):
                continue
            kinds = ', '.join(sorted({k for k, _r in claimed}))
            out.append(Finding(
                self.id, mod.relpath, node.lineno,
                f'event-form string drifts from the incident grammar: '
                f'it starts like event(s) [{kinds}] but matches no '
                f'EVENT_PATTERNS regex — fix the form, extend the '
                f'grammar, or suppress with a reason if this is '
                f'narration, not an event', node.col_offset))
        return out
