"""``python -m kfac_pytorch_tpu.analysis`` == ``kfac-lint``."""

import sys

from kfac_pytorch_tpu.analysis.cli import main

sys.exit(main())
