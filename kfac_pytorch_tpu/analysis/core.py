"""Framework core: findings, suppressions, the baseline ratchet, the
runner, and the static readers that give every rule one source of truth.

Nothing in here (or in any rule) imports the code under analysis — the
registries a rule needs are lifted out of their defining modules with
``ast`` (:meth:`RepoContext.static_literal`), so ``kfac-lint`` runs on a
bare stdlib Python and cannot be broken by an import-time bug in the
tree it is linting.
"""

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: the files the default run scans, relative to the repo root. Tests are
#: deliberately out: they monkeypatch, fake preconditioners and read
#: scratch env vars by design; the contracts below bind the shipped
#: tree. (A rule further narrows this through its ``scope``.)
DEFAULT_ROOTS = ('kfac_pytorch_tpu', 'examples', 'scripts', 'bench.py')

#: suppression comment grammar::
#:
#:     x = 1  # kfac-lint: disable=rule-id[,rule-id] [-- reason]
#:
#: on the flagged line or the line directly above it; or, anywhere in a
#: file, ``# kfac-lint: disable-file=rule-id[,rule-id] [-- reason]`` to
#: waive the rule for the whole file. The reason is free text for the
#: reviewer; the linter only parses the ids.
_SUPPRESS_RE = re.compile(
    r'#\s*kfac-lint:\s*(disable(?:-file)?)=([\w,-]+)')


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation. ``key`` (see :func:`finding_key`) is what the
    baseline pins — it hangs off the *content* of the flagged line, not
    its number, so unrelated edits above it don't churn the baseline."""
    rule: str
    path: str            # repo-relative, posix separators
    line: int            # 1-indexed
    message: str
    col: int = 0

    def render(self) -> str:
        return f'{self.path}:{self.line}:{self.col} [{self.rule}] {self.message}'


def finding_key(f: Finding, line_text: str) -> str:
    norm = ' '.join(line_text.split())
    return f'{f.rule}:{f.path}:{norm}'


class ModuleInfo:
    """A parsed source file plus everything rules repeatedly need."""

    def __init__(self, root: str, relpath: str):
        self.root = root
        self.relpath = relpath.replace(os.sep, '/')
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, encoding='utf-8') as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.text,
                                                     filename=self.relpath)
        except SyntaxError as e:          # pragma: no cover - repo parses
            self.tree = None
            self.parse_error = e
        self._suppressed = self._scan_suppressions()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ''

    def _scan_suppressions(self):
        per_line: Dict[int, set] = {}
        whole_file: set = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            ids = {r for r in m.group(2).split(',') if r}
            if m.group(1) == 'disable-file':
                whole_file |= ids
            else:
                per_line.setdefault(i, set()).update(ids)
        return per_line, whole_file

    def is_suppressed(self, rule_id: str, lineno: int) -> bool:
        per_line, whole_file = self._suppressed
        if rule_id in whole_file:
            return True
        for ln in (lineno, lineno - 1):
            if rule_id in per_line.get(ln, set()):
                return True
        return False


class RepoContext:
    """Shared per-run state: the repo root, the module cache, and the
    statically-read registries (one source of truth, zero imports)."""

    def __init__(self, root: str):
        self.root = root
        self._modules: Dict[str, ModuleInfo] = {}
        self._literals: Dict[Tuple[str, str], object] = {}

    def module(self, relpath: str) -> ModuleInfo:
        relpath = relpath.replace(os.sep, '/')
        if relpath not in self._modules:
            self._modules[relpath] = ModuleInfo(self.root, relpath)
        return self._modules[relpath]

    def static_literal(self, relpath: str, name: str):
        """The literal value of a module-level ``NAME = <literal>``
        assignment in ``relpath``, evaluated without importing it.
        Handles plain literals, tuples/lists/dicts/sets of literals,
        and ``frozenset({...})``. Raises ``KeyError`` if absent."""
        cache_key = (relpath, name)
        if cache_key in self._literals:
            return self._literals[cache_key]
        tree = self.module(relpath).tree
        if tree is None:
            raise KeyError(f'{relpath} failed to parse')
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                targets, value = [node.target.id], node.value
            else:
                continue
            if name not in targets:
                continue
            if (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id == 'frozenset' and value.args):
                value = value.args[0]
            try:
                lit = ast.literal_eval(value)
            except ValueError:
                raise KeyError(
                    f'{relpath}:{name} is not a static literal') from None
            self._literals[cache_key] = lit
            return lit
        raise KeyError(f'no module-level {name} in {relpath}')


class Rule:
    """Base class. Subclasses set ``id``/``summary``/``invariant``/
    ``caught`` (the README table columns) and implement ``check``."""

    id: str = ''
    summary: str = ''
    #: the project invariant this rule encodes (README table)
    invariant: str = ''
    #: which past PR's review-round bug it would have caught (README table)
    caught: str = ''

    def scope(self, relpath: str) -> bool:
        """Whether this rule looks at ``relpath`` at all."""
        return True

    def check(self, mod: ModuleInfo, ctx: RepoContext) -> List[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # new (not baselined, not suppressed)
    baselined: List[Finding]
    stale_baseline: List[str]        # baseline keys no finding matched
    suppressed: int
    files_scanned: int
    rules_run: Tuple[str, ...]

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.stale_baseline)

    def to_json(self) -> dict:
        return {
            'version': 1,
            'failed': self.failed,
            'files_scanned': self.files_scanned,
            'rules_run': list(self.rules_run),
            'suppressed': self.suppressed,
            'findings': [dataclasses.asdict(f) for f in self.findings],
            'baselined': [dataclasses.asdict(f) for f in self.baselined],
            'stale_baseline': list(self.stale_baseline),
        }


def discover_files(root: str, roots: Sequence[str] = DEFAULT_ROOTS
                   ) -> List[str]:
    out = []
    for entry in roots:
        top = os.path.join(root, entry)
        if os.path.isfile(top) and entry.endswith('.py'):
            out.append(entry)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ('__pycache__', '.git'))
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, '/'))
    return sorted(out)


def load_baseline(path: str) -> Dict[str, str]:
    """``lint-baseline.json``: finding key -> written justification.
    Every entry MUST carry a non-empty justification — an unexplained
    baseline entry is itself a lint error (enforced in run_lint)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        doc = json.load(f)
    entries = doc.get('entries', doc) if isinstance(doc, dict) else {}
    return {str(k): str(v) for k, v in entries.items()}


def write_baseline(path: str, entries: Dict[str, str]) -> None:
    doc = {
        '_comment': (
            'kfac-lint ratchet: accepted pre-existing findings, each '
            'with a justification. New findings never land here '
            'silently (the CI gate fails); fixed findings make their '
            'entry stale, which also fails until it is deleted.'),
        'entries': dict(sorted(entries.items())),
    }
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write('\n')


def run_lint(root: str,
             rules: Sequence[Rule],
             rule_ids: Optional[Sequence[str]] = None,
             roots: Sequence[str] = DEFAULT_ROOTS,
             baseline: Optional[Dict[str, str]] = None,
             collect: Optional[Callable[[Finding], None]] = None
             ) -> LintResult:
    """Run ``rules`` (optionally filtered to ``rule_ids``) over the
    repo at ``root`` and fold in suppressions and the baseline."""
    active = [r for r in rules
              if rule_ids is None or r.id in set(rule_ids)]
    if rule_ids is not None:
        known = {r.id for r in rules}
        unknown = sorted(set(rule_ids) - known)
        if unknown:
            raise KeyError(f'unknown rule id(s) {unknown}; '
                           f'known: {sorted(known)}')
    ctx = RepoContext(root)
    files = discover_files(root, roots)
    raw: List[Tuple[Finding, str]] = []   # (finding, flagged line text)
    suppressed = 0
    for rel in files:
        mod = ctx.module(rel)
        if mod.parse_error is not None:   # pragma: no cover - repo parses
            raw.append((Finding('parse', rel, mod.parse_error.lineno or 0,
                                f'syntax error: {mod.parse_error.msg}'), ''))
            continue
        for rule in active:
            if not rule.scope(rel):
                continue
            for f in rule.check(mod, ctx):
                if mod.is_suppressed(f.rule, f.line):
                    suppressed += 1
                    continue
                if collect is not None:
                    collect(f)
                raw.append((f, mod.line_text(f.line)))
    baseline = dict(baseline or {})
    new: List[Finding] = []
    base: List[Finding] = []
    matched_keys = set()
    for f, line_text in raw:
        key = finding_key(f, line_text)
        if key in baseline:
            # the entry is not STALE either way — the site still exists;
            # what varies is whether the justification earns the waiver
            matched_keys.add(key)
            just = baseline[key].strip()
            if not just or just.upper().startswith('TODO'):
                new.append(dataclasses.replace(
                    f, message=f.message + ' [baselined without a '
                    'justification — write one or fix it]'))
                continue
            base.append(f)
        else:
            new.append(f)
    # stale = fixed-but-not-deleted, judged only for the rules that RAN:
    # a --rule-filtered run must not condemn entries it never re-checked
    active_ids = {r.id for r in active}
    stale = sorted(k for k in set(baseline) - matched_keys
                   if k.split(':', 1)[0] in active_ids)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    base.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=new, baselined=base, stale_baseline=stale,
                      suppressed=suppressed, files_scanned=len(files),
                      rules_run=tuple(r.id for r in active))


def baseline_entries_for(result: LintResult, ctx_root: str,
                         justification: str = 'TODO: justify or fix'
                         ) -> Dict[str, str]:
    """Keys for ``--write-baseline``: every current finding, stamped
    with a placeholder justification the author must replace (an empty
    or TODO justification still fails the run — see run_lint)."""
    ctx = RepoContext(ctx_root)
    out = {}
    for f in result.findings + result.baselined:
        line_text = ctx.module(f.path).line_text(f.line)
        out[finding_key(f, line_text)] = justification
    return out
