"""Small shared AST helpers for the rules (stdlib ``ast`` only)."""

import ast
from typing import Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_with_func(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """Yield ``(node, enclosing_function_name)`` pairs; '<module>' at
    module level. The *nearest* enclosing def wins (nested defs give
    the inner name), matching how the coord allowlist names sites."""

    def visit(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        yield node, func
        for child in ast.iter_child_nodes(node):
            yield from visit(child, func)

    yield from visit(tree, '<module>')


def func_defs(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Every (qualname, def) in the module: ``f``, ``Class.m``,
    ``outer.<locals>.inner``."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + child.name
                yield qual, child
                yield from visit(child, qual + '.<locals>.')
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, prefix + child.name + '.')
            else:
                yield from visit(child, prefix)

    yield from visit(tree, '')


def docstring_linenos(tree: ast.AST) -> set:
    """Line ranges occupied by docstrings (module/class/function first
    statements) — string-scanning rules skip them."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, 'body', [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                c = body[0].value
                for ln in range(c.lineno, (c.end_lineno or c.lineno) + 1):
                    out.add(ln)
    return out
