"""TPU-native distributed K-FAC second-order optimization framework.

A from-scratch JAX/XLA re-design of the capabilities of lzhangbv/kfac_pytorch
(reference mounted at /root/reference): four distributed K-FAC preconditioner
variants (``inverse``, ``eigen``, ``inverse_dp``, ``eigen_dp``) behind the same
factory surface (reference: kfac/__init__.py:8-16, kfac/dp_kfac.py:4-39), built
TPU-first:

- Kronecker-factor statistics and preconditioning are pure-functional JAX ops
  batched onto the MXU (ops/).
- Activation / output-gradient capture replaces torch module hooks
  (reference: kfac/kfac_preconditioner_base.py:122-149) with Flax collections +
  a differentiable output-tap (capture.py, nn.py).
- Distribution replaces Horovod/NCCL/MPI (reference: kfac/backend.py,
  packages/tcmm/) with jax.sharding.Mesh + shard_map + XLA collectives over
  ICI/DCN (parallel/).
- Per-layer eigendecomposition work is padded into size-bucketed stacked
  arrays sharded over the mesh so eigh runs as one batched sharded XLA op —
  the TPU-idiomatic form of tcmm's multiBcast fused compute+broadcast
  (reference: packages/tcmm/src/communicator.cpp:75-117).
"""

try:
    from kfac_pytorch_tpu import compat as _compat
except ModuleNotFoundError as _e:  # pragma: no cover - jax-less lanes
    if _e.name not in ('jax', 'jaxlib'):
        raise
    # jax-less environments (the CI fleet-sim and lint jobs, a bare
    # coordination host) still get the stdlib-only planes below —
    # coord/, service/, resilience/, sim/, perfmodel — while the
    # optimizer surface stays absent and any use of it raises the
    # original, informative ModuleNotFoundError.
    _compat = None

if _compat is not None:
    _compat.install()  # jax.shard_map on older jax (see compat.py)

    from kfac_pytorch_tpu.preconditioner import (
        KFAC, KFACHyperParams, KFACState)
    from kfac_pytorch_tpu.scheduler import KFACParamScheduler
    from kfac_pytorch_tpu.health import HealthConfig, HealthState
    from kfac_pytorch_tpu import capture
    from kfac_pytorch_tpu import faults
    from kfac_pytorch_tpu import nn
    from kfac_pytorch_tpu import ops

from kfac_pytorch_tpu import resilience  # jax-free (elastic lazy-imports)

# Variant registry, mirroring the reference factory surface
# (reference: kfac/__init__.py:8-16) plus the beyond-reference 'ekfac'
# (George et al. 2018: per-example second moments in the joint
# Kronecker eigenbasis replace the eigenvalue outer product).
KFAC_VARIANTS = ('inverse', 'eigen', 'inverse_dp', 'eigen_dp', 'ekfac',
                 'ekfac_dp')


def get_kfac_module(kfac='eigen_dp'):
    """Return a KFAC factory pre-bound to a variant name.

    Parity with ``kfac.get_kfac_module`` (reference: kfac/__init__.py:15-16):
    the returned callable accepts the same hyper-parameters as ``KFAC``.
    """
    if kfac not in KFAC_VARIANTS:
        raise KeyError(f"unknown kfac variant {kfac!r}; choose from {KFAC_VARIANTS}")
    if _compat is None:
        raise ModuleNotFoundError(
            'jax is not installed: the K-FAC optimizer surface is '
            'unavailable (only the coordination/service/resilience/sim '
            'planes are importable in this environment)')

    def factory(*args, **kwargs):
        kwargs.setdefault('variant', kfac)
        return KFAC(*args, **kwargs)

    return factory


def DP_KFAC(*args, inv_type='eigen', **kwargs):
    """Distributed-preconditioning K-FAC facade.

    Parity with ``kfac.DP_KFAC`` (reference: kfac/dp_kfac.py:4-39): selects the
    eigen or explicit-inverse DP variant by ``inv_type``.
    """
    if _compat is None:
        raise ModuleNotFoundError(
            'jax is not installed: the K-FAC optimizer surface is '
            'unavailable (only the coordination/service/resilience/sim '
            'planes are importable in this environment)')
    variant = 'eigen_dp' if inv_type == 'eigen' else 'inverse_dp'
    kwargs.setdefault('variant', variant)
    return KFAC(*args, **kwargs)


__all__ = [
    'KFAC', 'KFACHyperParams', 'KFACState', 'KFACParamScheduler',
    'KFAC_VARIANTS', 'get_kfac_module', 'DP_KFAC', 'capture', 'nn', 'ops',
]
