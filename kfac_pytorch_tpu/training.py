"""Training-loop integration: the canonical K-FAC + SGD step.

The reference hot loop (examples/pytorch_cifar10_resnet.py:292-327) is

    zero_grad -> forward (hooks save a) -> backward (hooks save g)
    -> optimizer.synchronize (grad allreduce) -> preconditioner.step()
    -> optimizer.step()

Here the whole iteration is ONE jitted function per (update_factors,
update_inverse) combination — the steps-%-freq gating picks a compiled
variant on the host, so non-update steps never pay capture or
decomposition cost (the hook-gating semantics of
kfac_preconditioner_base.py:122-130 at zero runtime price). Under a mesh
the step runs inside shard_map: forward/backward on the local batch shard,
param grads psummed by autodiff (the gradient allreduce), K-FAC engine
collectives over the same axis.
"""

import functools
from typing import Any, Callable, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_pytorch_tpu import capture, faults
from kfac_pytorch_tpu import health as health_lib
from kfac_pytorch_tpu.parallel import collectives as coll
from kfac_pytorch_tpu.preconditioner import KFACHyperParams


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    kfac_state: Any
    extra_vars: Any  # batch_stats etc. (non-param collections)
    # numerical-health counters (health.HealthState) — None when the
    # guard is disabled; defaulted so pre-health TrainState constructions
    # (and checkpoints) keep working unchanged
    health: Any = None


def sgd(lr_schedule, momentum=0.9, weight_decay=0.0, nesterov=False):
    """torch.optim.SGD-equivalent optax chain (reference harness optimizer,
    examples/pytorch_cifar10_resnet.py:222-229): grad + wd*param, then
    momentum buffer, then lr scaling. K-FAC preconditioning happens before
    this chain, matching preconditioner.step() -> optimizer.step()."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(optax.scale_by_learning_rate(lr_schedule))
    return optax.chain(*parts)


class WorldRescale(NamedTuple):
    """What the batch geometry and learning rate become after an
    elastic world change (:func:`world_change_rescale`)."""
    old_world: int
    new_world: int
    global_batch: int          # achieved global batch AFTER the change
    per_host_batch: int        # achieved per-host batch AFTER the change
    lr: float                  # rescaled learning rate
    lr_factor: float           # lr multiplier actually applied

    def log_line(self):
        """The machine-greppable trainer protocol line
        (``incident.EVENT_PATTERNS`` 'world_rescale'): emit it verbatim
        so the churn timeline can show what the hyper-parameters became
        on each shrink/grow."""
        return (f'WORLD_RESCALE from_world={self.old_world} '
                f'to_world={self.new_world} '
                f'global_batch={self.global_batch} '
                f'lr={self.lr:g} lr_factor={self.lr_factor:g}')


def world_change_rescale(old_world, new_world, *, lr,
                         global_batch=None, per_host_batch=None,
                         lr_scaling='linear'):
    """Batch-size / learning-rate hook for an elastic shrink or grow:
    liveness is the supervisor's job, this keeps the ACCURACY contract
    across the world change.

    Exactly one of ``global_batch`` / ``per_host_batch`` names the
    deployment's batch invariant:

    - ``global_batch``: the GLOBAL batch is fixed (single-process
      trainers whose loader already produces the full batch; pods that
      re-split a fixed token budget). The per-host share re-derives as
      ``ceil(global / new_world)`` and the optimization trajectory is
      unchanged, so ``lr_factor`` is exactly 1 — the hook's job is to
      RECORD that nothing needed rescaling.
    - ``per_host_batch``: the PER-HOST batch is fixed (the common pod
      shape — each host feeds its local batch and the global batch IS
      ``per_host * world``). The global batch scales with the world, and
      the lr follows it under ``lr_scaling``: ``'linear'`` (Goyal et
      al. — the rule the reference's warmup_multistep scale already
      applies at launch time), ``'sqrt'``, or ``'none'`` (record only).

    Returns a :class:`WorldRescale`; trainers log ``result.log_line()``
    (the ``world_rescale`` event form) and apply ``result.lr`` /
    ``result.per_host_batch``. Typically wired through
    ``resilience.elastic_resume(on_world_change=...)`` so the hook
    fires exactly when a cross-world transport happened.
    """
    old_world, new_world = int(old_world), int(new_world)
    if old_world < 1 or new_world < 1:
        raise ValueError('world sizes must be >= 1, got '
                         f'{old_world} -> {new_world}')
    if (global_batch is None) == (per_host_batch is None):
        raise ValueError('pass exactly one of global_batch / '
                         'per_host_batch (the batch invariant)')
    if lr_scaling not in ('linear', 'sqrt', 'none'):
        raise ValueError(f'lr_scaling must be linear/sqrt/none, '
                         f'got {lr_scaling!r}')
    if global_batch is not None:
        global_batch = int(global_batch)
        per_host = max(1, -(-global_batch // new_world))  # ceil div
        factor = 1.0
        new_global = global_batch
    else:
        per_host = int(per_host_batch)
        old_global = per_host * old_world
        new_global = per_host * new_world
        ratio = new_global / old_global
        factor = {'linear': ratio, 'sqrt': float(np.sqrt(ratio)),
                  'none': 1.0}[lr_scaling]
    return WorldRescale(old_world=old_world, new_world=new_world,
                        global_batch=new_global, per_host_batch=per_host,
                        lr=float(lr) * factor, lr_factor=factor)


def _warm_basis_gate(precond, seen, step, ui, ub):
    """Host-side warm/cold decision for a full decomposition, mutating
    the run's ``seen`` record: warm only once a prior full exists (the
    stored basis must be orthogonal, not zeros), and every
    ``cold_restart_every``-th full goes cold to reset the orthogonality
    error the chained basis ``Q <- Q @ V'`` accumulates. An explicit
    iterative ``decomp_impl`` (``precond.warm_impl``) warms through the
    same gate — the tuner's ladder rung needs no separate
    ``warm_start_basis`` opt-in."""
    streak = seen.get('warm_streak', 0)
    warm = ((getattr(precond, 'warm_start_basis', False)
             or getattr(precond, 'warm_impl', False))
            and 'last_full' in seen
            and streak < getattr(precond, 'cold_restart_every', 50))
    if ui and ub:
        seen['last_full'] = step
        seen['warm_streak'] = streak + 1 if warm else 0
    return warm


def build_train_step(model, tx, precond, loss_fn, axis_name=None, mesh=None,
                     extra_mutable=(), sync_extra_vars=True, donate=True,
                     dropout_seed=None, batch_specs=None, check_vma=None,
                     fisher_type='Femp', fisher_loss_fn=None,
                     fisher_sample_fn=None, fisher_seed=0, health='auto',
                     straggler=None, heartbeat=None, tracer=None,
                     autotune=None):
    """Build the per-iteration function family.

    Args:
      model: Flax module built from kfac_pytorch_tpu.nn layers.
      tx: optax transformation (e.g. ``sgd(...)``).
      precond: a set-up ``KFAC`` instance, or None for the pure-SGD baseline
        (the ``kfac=0`` convention, reference README.md:80).
      loss_fn: ``loss_fn(outputs, batch) -> scalar``, and it MUST be the
        LOCAL-mean loss: the mean over this shard's examples only.
        Under data parallelism do NOT psum/pmean-normalize the loss
        inside ``loss_fn`` — the step averages the GRADIENTS across the
        K-FAC world itself (``parallel.average_grads``) and pmeans the
        reported loss metric separately. Why it matters: the capture
        backward's cotangents feed the K-FAC G factors, whose scaling
        assumes local-mean cotangents; a globally-normalized loss
        multiplies every G by the shard count, so the preconditioner
        (and anything tuned against it — lr, damping) silently changes
        with the mesh shape. This exact mistake cost round 3 a day of
        debugging (scripts/repro_mpd_eigen_orthogonal_axis.py); a free
        trace-time guard (``capture.check_local_mean_loss``) now rejects
        it — unless ``check_vma=False``, which disables both the guard
        AND the cross-axis cotangent psums capture relies on (see README
        "Loss conventions").
      axis_name/mesh: data-parallel axis; None for single device.
      extra_mutable: extra mutable collections (e.g. ('batch_stats',)).
      sync_extra_vars: pmean mutated collections across the axis so
        replicated state stays replicated (BN running stats).
      batch_specs: shard_map PartitionSpec (or pytree of specs) for the
        batch; default ``P(axis_name)`` (data-parallel on axis 0). Pass
        e.g. ``P(None, 'seq')`` for sequence-parallel token streams.
      check_vma: shard_map varying-manual-axes checking. Default (None)
        enables it except when the environment routes attention through
        the Pallas interpreter (test-only; its block-index machinery
        rejects vma-tagged scalar-prefetch args). Pass an explicit bool
        when selecting ``block_impl='pallas_interpret'`` per-call instead
        of via KFAC_ATTN_IMPL.
      fisher_type: 'Femp' (default) estimates the Fisher from the
        empirical-gradient backward; 'F1mc' is the true-Fisher 1-sample MC
        estimator — on factor-update steps a second capture backward runs
        against labels sampled from the model's own predictive
        distribution, and its (a, g) feed the factors while the parameter
        update still uses the real-loss gradients. The reference declares
        this choice (examples/utils.py:82-90 generate_pseudo_labels) but
        never wires it into a trainer; here it is first-class. Both
        backwards live in one compiled program (XLA CSEs the shared
        forward), so the extra cost lands only on fac_update_freq steps.
      fisher_loss_fn: F1mc sampling loss ``(outputs, pseudo_labels) ->
        scalar`` (local mean). Default: softmax cross-entropy over the
        last axis, which covers classifiers and LM token heads.
      fisher_sample_fn: F1mc label sampler ``(rng, outputs) ->
        pseudo_labels``; must draw from the predictive distribution
        implied by ``fisher_loss_fn`` (override BOTH together — e.g. a
        Gaussian head needs a Gaussian sampler, not the default
        categorical). Default: ``utils.losses.sample_pseudo_labels``.
      fisher_seed: base seed for the pseudo-label sampler (folded with the
        step counter and, under data parallelism, the device index).
      health: the in-jit numerical-health guard (health.py). 'auto'
        (default) inherits the preconditioner's ``health`` config (off
        for the pure-SGD baseline); True/False/HealthConfig override it
        explicitly — pass ``health=True`` to give a precond-less SGD run
        the bad-batch skip too. When enabled, the step screens the loss,
        gradients and captured factor statistics for NaN/Inf INSIDE the
        jitted program: a bad batch skips the optimizer AND factor-EMA
        updates via ``lax.cond`` (params/opt_state/m_A/m_G stay bit-
        identical to a schedule that never contained the batch), repeated
        failures climb a damping-escalation ladder and finally degrade
        the step to plain SGD until recovery (see health.HealthConfig).
        Metrics gain ``health/*`` counters (utils.metrics.HealthMonitor
        consumes them). The guard adds no compiled step variants and no
        per-step host sync: the skip decision is a replicated on-device
        scalar (one extra psum under a mesh).
      straggler: a ``resilience.StragglerGovernor`` (or None). When set,
        every host step ticks the governor with the inter-arrival time
        of step_fn calls — which includes the caller's blocking metric
        read and next-batch assembly, i.e. the true host step — and a
        sustained over-budget EMA stretches the preconditioner's
        ``fac_update_freq``/``kfac_update_freq`` through the same
        host-side freq gating the scheduler uses (restored on
        recovery): a slow host degrades preconditioner freshness
        instead of throughput.
      heartbeat: a ``resilience.PeerHeartbeat`` (or None). When set,
        every host step calls ``heartbeat.tick(step)`` — stamping the
        current step into the published liveness payload (so a peer's
        incident report can say how far the dead host got) and arming
        the silent-death chaos drill (``KFAC_FAULT_HB_STOP_STEP``).
        Liveness itself rides the heartbeat's own background thread,
        not this tick: a trainer wedged in a collective stops ticking
        but keeps beating, which is exactly the split the pod needs —
        the heartbeat answers "alive?", the watchdog answers
        "progressing?".
      autotune: an ``autotune.KnobController`` (or None). When set,
        every host step ticks the controller with the inter-arrival
        time of step_fn calls (the same full-host-step measurement the
        straggler governor uses) attributed to the PREVIOUS dispatch's
        phase set — the closed loop's measurement feed. The
        controller's knob changes flow through the preconditioner's
        single arbiter; a frequency change reuses this step_fn's
        compiled variant cache, a ``comm_precision`` change clears it
        (the arbiter invalidator registered below) so no stale program
        can keep the old wire dtype.
      tracer: an ``obs.trace.TraceRecorder`` (or None). When set, every
        dispatch is recorded as a ``kfac.dispatch`` span carrying the
        step index and the dispatched phase set in the exclude-parts
        ledger taxonomy. This span covers dispatch only (the call
        returns before the device finishes under async dispatch); the
        full host-side step span — including the blocking metric read —
        is ``PhaseTimers(tracer=...)``'s ``kfac.step``, so a trace
        shows both how long the host spent submitting and how long the
        step really took.

    Returns ``step_fn(state, batch, lr, damping) -> (state, metrics)``;
    dispatches between up to four compiled variants using the
    preconditioner's host-side update frequencies. With a
    ``KFAC(stagger=True)`` preconditioner, the first inverse update is
    still one full decomposition; afterwards every step dispatches the
    staggered variant (traced cohort index — the variant count does not
    grow with ``kfac_update_freq``), and the dispatch rebases the cohort
    layout whenever the scheduler or straggler governor rescaled the
    frequency. ``step_fn.last_phases`` names the K-FAC phases the last
    dispatch ran ('pred'/'stats'/'decomp'/'gather') for
    ``utils.metrics.PhaseTimers``.
    """
    if fisher_type not in ('Femp', 'F1mc'):
        raise ValueError(f'fisher_type must be Femp or F1mc, '
                         f'got {fisher_type!r}')
    if (axis_name is None
            and getattr(precond, 'mesh_axes', None) is not None):
        # mesh-planned preconditioner: the K-FAC world derives from the
        # mesh spec's data axes — inherit it so callers name the mesh
        # in exactly one place (KFAC(mesh_axes=...))
        axis_name = precond.axis_name
    if health == 'auto':
        health_cfg = getattr(precond, 'health', None)
    else:
        health_cfg = health_lib.resolve(health)
    # deterministic chaos faults (faults.py): the env snapshot happens
    # once, here, so the traced fault steps are static — enabling a fault
    # never changes the compiled-variant count or adds host syncs
    fault_cfg = faults.from_env()
    if fisher_loss_fn is None:
        def fisher_loss_fn(outputs, pseudo_labels):
            return optax.softmax_cross_entropy_with_integer_labels(
                outputs, pseudo_labels).mean()
    if fisher_sample_fn is None:
        from kfac_pytorch_tpu.utils.losses import sample_pseudo_labels
        fisher_sample_fn = sample_pseudo_labels

    def one_step(state, batch, hyper, update_factors, update_inverse,
                 update_basis=True, warm_basis=False, factors_only=False,
                 stagger_update=False, prefetch=False):
        x = batch['input']
        variables = {'params': state.params, **state.extra_vars}
        use_capture = precond is not None and update_factors
        rngs = None
        if dropout_seed is not None:
            key = jax.random.fold_in(jax.random.PRNGKey(dropout_seed),
                                     state.step)
            if axis_name is not None:
                # per-device dropout masks (DistributedSampler-style
                # decorrelation of the local batches)
                key = jax.random.fold_in(key, coll.axis_index(axis_name))
            rngs = {'dropout': key}

        if use_capture:
            loss, out, grads, acts, gs, mutated = \
                capture.value_and_grad_with_capture(
                    model, lambda o: loss_fn(o, batch), variables, x,
                    mutable=extra_mutable, axis_name=axis_name, rngs=rngs)
            # trace-time convention guard (free): the capture loss must
            # be the LOCAL mean, or every G factor scales with the
            # shard count (the round-3 postmortem bug)
            capture.check_local_mean_loss(loss, batch, axis_name)
            if fisher_type == 'F1mc':
                # true-Fisher MC estimate: re-capture (a, g) from a backward
                # against labels sampled from the model's own distribution;
                # the parameter update keeps the real-loss grads above.
                # 0xF15C domain tag keeps this stream distinct from the
                # dropout stream even when dropout_seed == fisher_seed.
                key = jax.random.fold_in(jax.random.PRNGKey(fisher_seed),
                                         0xF15C)
                key = jax.random.fold_in(key, state.step)
                if axis_name is not None:
                    key = jax.random.fold_in(key, coll.axis_index(axis_name))
                pseudo = fisher_sample_fn(key, jax.lax.stop_gradient(out))
                floss, _, _, acts, gs, _ = \
                    capture.value_and_grad_with_capture(
                        model, lambda o: fisher_loss_fn(o, pseudo),
                        variables, x, mutable=extra_mutable,
                        axis_name=axis_name, rngs=rngs)
                capture.check_local_mean_loss(floss, pseudo, axis_name)
        else:
            def plain_loss(params):
                out, mutated = model.apply(
                    {'params': params, **state.extra_vars}, x,
                    mutable=list(extra_mutable), rngs=rngs)
                return loss_fn(out, batch), (out, mutated)

            (loss, (out, mutated)), grads = jax.value_and_grad(
                plain_loss, has_aux=True)(state.params)
            acts = gs = None
            # same convention on the SGD path: average_grads below
            # divides the psummed grads by world size, so a pre-pmean'd
            # loss would double-normalize the update
            capture.check_local_mean_loss(loss, batch, axis_name)

        # chaos faults fire BEFORE the health screen — the screen is what
        # is being drilled (pass-through unless env-configured)
        grads = faults.corrupt_grads(fault_cfg, state.step, grads)
        acts, gs = faults.corrupt_captured(fault_cfg, state.step, acts, gs)

        loss_local = loss
        grads = coll.average_grads(grads, axis_name)
        loss = coll.pmean(loss, axis_name)

        def apply_update(hstate):
            """The normal K-FAC + optimizer update (the only path when
            the health guard is off; the lax.cond true-branch otherwise).
            """
            kfac_state = state.kfac_state
            new_grads = grads
            precond_ok = jnp.ones((), bool)
            if precond is not None:
                h = hyper
                if health_cfg is not None:
                    # damping-escalation ladder: rung r multiplies the
                    # damping fed to decomposition + preconditioning
                    h = hyper.replace(damping=health_lib.effective_damping(
                        hstate, hyper.damping, health_cfg))
                pgrads, kfac_state = precond.step(
                    kfac_state, grads, acts, gs, hyper=h,
                    update_factors=update_factors,
                    update_inverse=update_inverse,
                    update_basis=update_basis,
                    warm_basis=warm_basis, factors_only=factors_only,
                    stagger_update=stagger_update, prefetch=prefetch,
                    axis_name=axis_name)
                if health_cfg is None:
                    new_grads = pgrads
                else:
                    # a non-finite preconditioner output (or the ladder's
                    # top rung) degrades THIS step to raw SGD gradients;
                    # factor statistics above still accumulated
                    precond_ok = capture.all_finite(pgrads)
                    use_precond = jnp.logical_and(
                        precond_ok,
                        jnp.logical_not(
                            health_lib.degraded(hstate, health_cfg)))
                    new_grads = jax.tree.map(
                        lambda p, r: jnp.where(use_precond, p, r),
                        pgrads, grads)

            updates, opt_state = tx.update(new_grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)

            extra_vars = dict(state.extra_vars)
            for k in extra_mutable:
                if k in mutated:
                    v = mutated[k]
                    if sync_extra_vars:
                        v = coll.pmean(v, axis_name)
                    extra_vars[k] = v

            if health_cfg is not None:
                hstate = health_lib.on_good_batch(hstate, health_cfg,
                                                  precond_ok)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state,
                                 kfac_state=kfac_state,
                                 extra_vars=extra_vars, health=hstate)

        if health_cfg is None:
            return apply_update(state.health), {'loss': loss}

        def skip_update(hstate):
            """Bad batch: params, opt_state, factor EMAs and extra_vars
            stay bit-exactly as if the batch never happened; only the
            step counters and health counters advance."""
            kfac_state = state.kfac_state
            if kfac_state is not None:
                # keep KFACState.step in lockstep with TrainState.step so
                # in-engine fault steps stay aligned with trainer steps
                kfac_state = kfac_state.replace(step=kfac_state.step + 1)
            return state.replace(
                step=state.step + 1, kfac_state=kfac_state,
                health=health_lib.on_bad_batch(hstate, health_cfg))

        # one replicated scalar decides the branch — no host sync, and
        # every device agrees (batch_ok psums the per-shard bad flags)
        ok = health_lib.batch_ok(axis_name, grads, loss_local, acts, gs)
        new_state = jax.lax.cond(ok, apply_update, skip_update,
                                 state.health)
        mets = {'loss': loss}
        mets.update({'health/' + k: v for k, v in
                     health_lib.metrics(new_state.health, ok).items()})
        return new_state, mets

    state_specs_cache = {}

    def make_variant(update_factors, update_inverse, update_basis=True,
                     warm_basis=False, factors_only=False,
                     stagger_update=False, prefetch=False):
        fn = functools.partial(one_step, update_factors=update_factors,
                               update_inverse=update_inverse,
                               update_basis=update_basis,
                               warm_basis=warm_basis,
                               factors_only=factors_only,
                               stagger_update=stagger_update,
                               prefetch=prefetch)
        if axis_name is None:
            return jax.jit(fn, donate_argnums=(0,) if donate else ())
        kspecs = (precond.state_pspecs(axis_name) if precond is not None
                  else P())
        # health counters are replicated scalars (P() matches the empty
        # subtree too when the guard is off)
        sspecs = TrainState(step=P(), params=P(), opt_state=P(),
                            kfac_state=kspecs, extra_vars=P(), health=P())
        bspecs = P(axis_name) if batch_specs is None else batch_specs
        from .parallel.ring_attention import interpreted_attention_active
        vma = (not interpreted_attention_active() if check_vma is None
               else check_vma)
        sharded = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(sspecs, bspecs, P()),
            out_specs=(sspecs, P()),
            check_vma=vma)
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    variants = {}
    seen_inverse = {}  # host-side: does a decomposition exist yet?

    def step_fn(state, batch, lr=None, damping=None):
        step = int(state.step)
        # straggler governor: measure the inter-arrival of host steps
        # (tick BEFORE the fault hooks so an injected slow step lands in
        # the NEXT tick's interval, like any real stall would)
        if straggler is not None:
            straggler.tick(step)
        if autotune is not None:
            # the interval that just ended covered the PREVIOUS
            # dispatch's phase set — attribute it there, like the
            # PhaseTimers wall-time bucketing
            autotune.tick(step, step_fn.last_phases)
        if heartbeat is not None:
            heartbeat.tick(step)
        # host-side chaos drills (all no-ops unless env-configured):
        # SIGTERM (PreemptionGuard), crash (supervisor restart), hang
        # (step watchdog), slow (straggler governor)
        faults.maybe_sigterm(fault_cfg, step)
        faults.maybe_crash(fault_cfg, step)
        faults.maybe_hang(fault_cfg, step)
        faults.maybe_slow(fault_cfg, step,
                          sleep=(straggler.sleep if straggler is not None
                                 else None))
        if (precond is not None
                and getattr(precond, 'pending_replan', None)):
            # a queued live replan (the arbiter's applied comm_mode
            # switch, or a direct request_replan): apply it HERE — the
            # between-steps boundary where no traced program is running
            # — before anything below reads the preconditioner's config
            # or retraces against the (already-invalidated) variant
            # cache. A pure comm-mode switch carries the state verbatim;
            # a layout change transports it host-side.
            state = state.replace(
                kfac_state=precond.apply_pending_replan(state.kfac_state))
        if health_cfg is not None and state.health is None:
            # one-time upgrade of a pre-health TrainState (old checkpoint
            # or a hand-built state): done host-side BEFORE the jitted
            # call so every variant only ever sees one state structure
            state = state.replace(health=health_lib.HealthState.init())
        if (precond is not None and state.kfac_state is not None
                and getattr(precond, '_tracks_comm_err', False)
                and state.kfac_state.comm_err is None):
            # same one-time upgrade for the EF residual: a checkpoint
            # taken before comm_precision was enabled (or at fp32)
            # carries no residual — seed zeros host-side so every
            # variant sees one state structure
            state = state.replace(kfac_state=state.kfac_state.replace(
                comm_err=precond._zero_comm_err()))
        if (precond is not None and state.kfac_state is not None
                and not getattr(precond, '_tracks_comm_err', False)
                and state.kfac_state.comm_err is not None):
            # the DOWNGRADE direction of the same upgrade: the autotuner
            # (or a restart at fp32) switched the wire dtype off a lossy
            # mode mid-run — drop the EF residual host-side so every
            # variant sees one state structure; the residual is a
            # correction term, never load-bearing (discarding it costs
            # one reduce's worth of feedback, the same contract the
            # lossy-checkpoint-into-fp32 restore already accepts)
            state = state.replace(kfac_state=state.kfac_state.replace(
                comm_err=None))
        if 'yes' not in seen_inverse:
            # one-time: a restored checkpoint may already carry a
            # decomposition (utils/checkpoint.py include_kfac=True)
            seen_inverse['yes'] = bool(
                state.kfac_state is not None
                and any(bool(jnp.any(x != 0))
                        for x in jax.tree.leaves(state.kfac_state.decomp)))
        st = False
        pf = False
        if precond is None:
            uf = ui = False
            ub, warm = True, False
        else:
            # hook_enabled=False freezes factor capture/updates (reference
            # set_hook_enabled, kfac_preconditioner_base.py:117-130); the
            # existing decomposition keeps preconditioning. Before ANY
            # decomposition exists the gradients pass through unmodified
            # while factor statistics still accumulate on schedule (the
            # reference would have no factors to read at all here).
            enabled = getattr(precond, 'hook_enabled', True)
            uf = enabled and precond.should_update_factors(step)
            st = (getattr(precond, 'stagger', False) and enabled
                  and seen_inverse['yes'])
            if st:
                # staggered refresh: after the first (full) decomposition
                # EVERY step decomposes one cost-balanced cohort — the
                # cohort index is traced, so this is ONE compiled variant
                # per uf setting, not one per cohort
                ui, ub, warm = False, True, False
            else:
                ui = enabled and precond.should_update_inverse(step)
                # eigenvalue-only refresh needs a basis to refresh: the
                # first inverse update of this run is always a full
                # decomposition (no last_full yet — covers fresh starts,
                # resumes, and the stagger cold start alike)
                ub = (not seen_inverse['yes']
                      or precond.should_update_basis(
                          step, seen_inverse.get('last_full')))
                warm = _warm_basis_gate(precond, seen_inverse, step, ui, ub)
                # cross-step prefetch: publish this inverse update's
                # gathered table for the NEXT step — only once a prior
                # table exists (the first decomposition must be consumed
                # same-step or the pred would read zeros)
                pf = (getattr(precond, 'comm_prefetch', False) and ui
                      and seen_inverse['yes'])
                seen_inverse['yes'] = seen_inverse['yes'] or ui
                if not ui:
                    ub, warm = True, False  # unused w/o an inverse update
                if not ub:
                    warm = False        # refresh path has no eigh to warm
        key = (uf, ui, ub, warm, pf)
        if st:
            # the cohort layout derives from kfac_update_freq: a
            # scheduler/straggler rescale rebases it here, and the cohort
            # count rides in the cache key so the rebuilt (static) tables
            # get a fresh trace — same freq back again reuses the old one
            layout = precond.rebase_cohorts()
            key = (uf, 'stagger', layout.num_cohorts)
            if key not in variants:
                variants[key] = make_variant(uf, False, stagger_update=True)
        if precond is not None and not seen_inverse['yes']:
            key = (uf, False, 'factors_only')
            if key not in variants:
                variants[key] = make_variant(uf, False, factors_only=True)
        if key not in variants:
            variants[key] = make_variant(uf, ui, ub, warm, prefetch=pf)
        # host-visible phase set of THIS dispatch (consumed by
        # utils.metrics.PhaseTimers for the kfac_phase_ms epoch suffix)
        if precond is None:
            step_fn.last_phases = ()
        elif not seen_inverse['yes']:
            step_fn.last_phases = ('stats',) if uf else ()
        else:
            ph = ['pred']
            if uf:
                ph.append('stats')
            if ui or st:
                ph.append('decomp')
                if precond.comm_mode == 'inverse':
                    ph.append('gather')
            step_fn.last_phases = tuple(ph)
        hyper = KFACHyperParams(
            lr=jnp.float32(lr if lr is not None
                           else getattr(precond, 'lr', 0.0)),
            damping=jnp.float32(damping if damping is not None
                                else getattr(precond, 'damping', 0.0)))
        # does THIS dispatch publish a gathered table for the NEXT step?
        # (stagger's double-buffered cohort gather, or comm_prefetch on a
        # full inverse update) — recorded as overlapping schedule spans
        # so a trace shows the CommunicateInverse gather riding under the
        # pred einsums with no same-step consumer
        prefetched_gather = (pf or st) and (
            precond is not None and precond.comm_mode == 'inverse'
            and 'gather' in step_fn.last_phases)
        try:
            if tracer is not None:
                from kfac_pytorch_tpu.obs.trace import taxonomy_phases
                with tracer.span('kfac.dispatch', cat='kfac.step',
                                 step=step,
                                 phases=taxonomy_phases(
                                     step_fn.last_phases)):
                    if prefetched_gather:
                        cohort = (step % layout.num_cohorts if st
                                  else None)
                        with tracer.span(
                                'kfac.Precondition', cat='kfac.sched',
                                step=step, table='stored'), \
                             tracer.span(
                                'kfac.CommunicateInverse.prefetch',
                                cat='kfac.sched', step=step,
                                cohort=cohort,
                                consumer_step=step + 1):
                            return variants[key](state, batch, hyper)
                    return variants[key](state, batch, hyper)
            return variants[key](state, batch, hyper)
        except Exception as e:
            # per-call block_impl='pallas_interpret' cannot be seen by the
            # check_vma auto-detection (it only reads KFAC_ATTN_IMPL), and
            # the resulting shard_map trace error is cryptic — point at
            # the escape hatch
            msg = str(e)
            if check_vma is None and ('vma' in msg or 'Varying' in msg
                                      or 'varying' in msg):
                raise RuntimeError(
                    msg + '\n[kfac_pytorch_tpu] If this model routes '
                    'attention through the Pallas interpreter per-call '
                    "(block_impl='pallas_interpret') rather than via "
                    'KFAC_ATTN_IMPL, pass check_vma=False to '
                    'build_train_step.') from e
            raise

    # Warm-tracking host state, exposed for checkpoint/resume: three
    # scalars ('yes', 'last_full', 'warm_streak') that are per-process
    # and NOT part of the on-device TrainState. Resume semantics WITHOUT
    # restoring it are safe by construction: the first inverse update of
    # a resumed run is always a full cold decomposition (no 'last_full'
    # yet) and the cold_restart_every streak restarts from zero — only
    # the *cadence* of future cold restarts shifts, never correctness.
    # Callers wanting bit-identical cadence across preemption can dump
    # this dict (plain ints/bools, json-safe) next to the checkpoint and
    # assign it back onto the new step_fn: step_fn.warm_tracking.update(
    # saved). Pinned by tests/test_training.py::
    # test_warm_tracking_resume_semantics.
    step_fn.warm_tracking = seen_inverse
    # which K-FAC phases the LAST dispatch ran ('pred'/'stats'/'decomp'/
    # 'gather') — host-side knowledge the examples feed to
    # utils.metrics.PhaseTimers together with the step's wall time, so
    # epoch lines can attribute time per phase (runlog.kfac_phase_suffix)
    step_fn.last_phases = ()
    # the jitted variant cache + constructor, exposed for introspection:
    # scripts/comm_count.py builds a variant via make_variant and lowers
    # it WITHOUT executing a step (AOT lower/compile only)
    step_fn.variants = variants
    step_fn.make_variant = make_variant
    if precond is not None:
        # trace-affecting knob changes (comm_precision / decomp_impl /
        # an applied comm_mode replan through the knob arbiter —
        # scheduler/straggler/tuner frequency changes are host-side
        # gating and deliberately NOT invalidating) clear the
        # compiled-variant cache so no stale program keeps the old wire
        # dtype or plan; the next dispatch retraces against the new
        # config
        def _invalidate_variants():
            variants.clear()
            # a replan may have dropped the stored decomposition (a
            # cross-method variant switch zeroes it): re-derive the
            # "seen a decomposition" record from the STATE on the next
            # dispatch, and restart the warm-streak bookkeeping — the
            # next full decomposition after any trace-affecting change
            # goes cold (never warm-seed across a swapped plan; only
            # the cold-restart cadence shifts, never correctness)
            for k in ('yes', 'last_full', 'warm_streak'):
                seen_inverse.pop(k, None)

        from kfac_pytorch_tpu.autotune import arbiter_for
        arbiter_for(precond).add_invalidator(_invalidate_variants)
    return step_fn


def init_train_state(model, tx, precond, rng, sample_input, health='auto'):
    """Initialize params, optimizer and K-FAC state (plus discovery of the
    capture layer metadata if the preconditioner isn't set up yet).

    ``health`` mirrors build_train_step's argument: 'auto' seeds the
    HealthState counters iff the preconditioner's guard is on; pass
    True/False/HealthConfig to override (match what the step uses —
    step_fn upgrades a missing HealthState on first call anyway).
    """
    # provide a dropout stream too: models that train with dropout (LSTM,
    # transformer) request it at init since their __call__ defaults to
    # train=True
    rngs = {'params': rng, 'dropout': jax.random.fold_in(rng, 1)}
    variables = capture.init(model, rngs, sample_input)
    params = variables.pop('params')
    kfac_state = None
    if precond is not None:
        if precond.plan is None:
            metas = capture.collect_layer_meta(
                model, {'params': params, **variables}, sample_input,
                rngs={'dropout': jax.random.fold_in(rng, 2)})
            precond.setup(metas)
        kfac_state = precond.init()
    if health == 'auto':
        health_cfg = getattr(precond, 'health', None)
    else:
        health_cfg = health_lib.resolve(health)
    hstate = (health_lib.HealthState.init() if health_cfg is not None
              else None)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=tx.init(params), kfac_state=kfac_state,
                      extra_vars=variables, health=hstate)
