"""The coordination-backend contract: the six primitives every fleet
protocol in this repo already implicitly uses.

The membership barriers (``resilience.elastic``), heartbeat leases
(``resilience.heartbeat``), lineage fencing, the durable job queue
(``service.queue``) and the capacity pool (``service.scheduler``) all
speak one implicit protocol: small JSON documents under hierarchical
keys, written atomically, read torn-tolerantly, compare-and-swapped via
an embedded epoch, scanned by prefix, and — for liveness — republished
on a cadence. :class:`CoordBackend` names those primitives explicitly:

- ``get(key) -> Versioned | None`` — torn/missing reads are ``None``
  (the skip-and-retry discipline every protocol reader follows).
- ``put(key, value)`` — atomic unconditional write.
- ``put_cas(key, value, expect_version)`` — versioned compare-and-swap;
  ``None`` expect means *create only if absent*, :data:`ANY` skips the
  check. Returns the new version, or ``None`` on a conflict — a
  conflict is an ANSWER (someone else moved the state), never an error.
- ``delete(key)`` / ``delete_prefix(prefix)`` — idempotent removal.
- ``list(prefix)`` / ``get_many(prefix)`` — prefix scans.
- ``lease(key, ttl, payload)`` — a liveness key the backend may expire
  when its owner stops refreshing (advisory on POSIX, enforced by the
  TCP KV server).
- ``watch(prefix)`` — poll-based change feed (puts/deletes since the
  previous poll) for consumers that would otherwise re-read whole
  trees.

Error model: every transient backend failure raises
:class:`CoordTimeout` (an :class:`OSError` subclass, so the existing
``except OSError`` miss-one-beat / skip-one-poll semantics in the
protocol layers degrade exactly as they do for a flaky shared
filesystem). :class:`RetryingBackend` wraps any backend with a
per-operation :class:`~kfac_pytorch_tpu.resilience.retry.RetryPolicy`
and raises :class:`CoordGiveUp` — loudly, with the machine-greppable
``[resilience: coord_gave_up=1]`` form — once the budget is spent, so
callers exit with the dedicated give-up rc instead of wedging.

Zero dependencies, jax-free (the heartbeat layer imports this).
"""

import contextlib
import logging
import threading

log = logging.getLogger(__name__)


def _res():
    # lazy: coord is imported BY the resilience package's submodules
    # (heartbeat, elastic) — a module-level import back into it would
    # make the import order matter; a call-time one cannot
    from kfac_pytorch_tpu import resilience
    return resilience


class CoordError(OSError):
    """Base class for coordination-backend failures. An ``OSError`` on
    purpose: the protocol layers' existing flaky-filesystem handling
    (miss one beat, skip one poll, retry next cycle) applies verbatim.
    """


class CoordTimeout(CoordError):
    """A transient backend failure (unreachable server, op timeout,
    injected unavailability window). Retryable."""


class CoordGiveUp(CoordError):
    """The retry budget for one operation is spent. Raised by
    :class:`RetryingBackend` after logging the loud give-up form;
    supervisors/schedulers exit :data:`~kfac_pytorch_tpu.coord.RC_COORD_LOST`
    on it instead of spinning against a dead coordination plane."""


class _Any:
    def __repr__(self):
        return '<coord.ANY>'


#: ``put_cas`` sentinel: skip the version check (unconditional write
#: through the CAS path — distinct from ``expect_version=None``, which
#: means "create only if the key does not exist yet").
ANY = _Any()


class Versioned:
    """A read result: the decoded JSON value plus the backend's opaque
    version token for it (feed it back to ``put_cas``)."""

    __slots__ = ('value', 'version')

    def __init__(self, value, version):
        self.value = value
        self.version = version

    def __iter__(self):  # tuple-unpack convenience: value, version = r
        yield self.value
        yield self.version

    def __repr__(self):
        return f'Versioned({self.value!r}, version={self.version!r})'


class Lease:
    """A liveness key: ``refresh`` republishes (restarting the TTL on
    backends that enforce one), ``release`` deletes. The POSIX backend
    cannot expire leases server-side — readers there judge liveness by
    sequence ADVANCE, which is the heartbeat monitor's contract anyway.
    """

    def __init__(self, backend, key, ttl):
        self.backend = backend
        self.key = key
        self.ttl = float(ttl)

    def refresh(self, payload):
        return self.backend.put(self.key, payload, ttl=self.ttl)

    def release(self):
        with contextlib.suppress(OSError):
            self.backend.delete(self.key)


class Watch:
    """Poll-based change feed over a key prefix.

    ``poll()`` returns ``{key: 'put' | 'delete'}`` for everything that
    changed since the previous poll (first poll: every existing key as
    ``'put'``). Built on version snapshots, so it works on any backend
    that implements ``list`` + ``get`` — no server-side subscription
    needed, and a missed poll coalesces instead of queueing.

    ``values`` holds the decoded values of the LAST poll's snapshot —
    the versioned scan returns them anyway, so a consumer that polls
    through a watch gets the current state for free and only has to
    re-decode the keys the poll named (O(changes) idle cost, which is
    the whole point of watching instead of re-reading the tree).
    """

    def __init__(self, backend, prefix):
        self.backend = backend
        self.prefix = str(prefix)
        self._versions = None
        self.values = {}

    def poll(self):
        snap = self.backend.get_many_versioned(self.prefix)
        now = {key: got.version for key, got in snap.items()}
        self.values = {key: got.value for key, got in snap.items()}
        prev = self._versions if self._versions is not None else {}
        self._versions = now
        changes = {}
        for key, ver in now.items():
            if prev.get(key) != ver:
                changes[key] = 'put'
        for key in prev:
            if key not in now:
                changes[key] = 'delete'
        return changes


def check_key(key):
    """Keys are relative ``/``-joined paths; reject escapes so a POSIX
    backend can never be walked out of its root."""
    key = str(key)
    if not key or key.startswith('/') or '\\' in key:
        raise ValueError(f'bad coordination key {key!r}')
    if any(part in ('', '.', '..') for part in key.split('/')):
        raise ValueError(f'bad coordination key {key!r}')
    return key


def check_prefix(prefix):
    """Prefixes share the key grammar ('' = everything, one trailing
    ``/`` allowed) — and the same escape rejection: a ``..`` prefix
    reaching ``delete_prefix`` must never walk a POSIX backend out of
    its root."""
    prefix = str(prefix)
    if not prefix:
        return prefix
    if prefix.startswith('/') or '\\' in prefix:
        raise ValueError(f'bad coordination prefix {prefix!r}')
    parts = prefix.split('/')
    if parts and parts[-1] == '':
        parts = parts[:-1]
    if any(part in ('', '.', '..') for part in parts):
        raise ValueError(f'bad coordination prefix {prefix!r}')
    return prefix


class CoordBackend:
    """Interface + shared conveniences. Subclasses implement ``get``,
    ``put``, ``put_cas``, ``delete``, ``delete_prefix`` and ``list``."""

    # -- required primitives ----------------------------------------------

    def get(self, key):
        raise NotImplementedError

    def put(self, key, value, *, indent=None, ttl=None):
        raise NotImplementedError

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        """``token``: optional idempotency token for replay-safe CAS
        over a lossy wire — a backend that can remember the last
        applied writer (the KV server) answers a REPLAY of the same
        token with the original success instead of a self-conflict.
        Local backends may ignore it (their CAS cannot time out
        mid-apply)."""
        raise NotImplementedError

    def delete(self, key):
        raise NotImplementedError

    def delete_prefix(self, prefix):
        raise NotImplementedError

    def list(self, prefix=''):
        raise NotImplementedError

    # -- derived ----------------------------------------------------------

    def get_many(self, prefix=''):
        """{key: value} for every readable key under ``prefix`` (torn
        keys skipped this scan, the protocol-reader discipline)."""
        out = {}
        for key in self.list(prefix):
            got = self.get(key)
            if got is not None:
                out[key] = got.value
        return out

    def get_many_versioned(self, prefix=''):
        """{key: Versioned} for every readable key under ``prefix`` —
        the change-feed scan (:class:`Watch`). Derived default is
        list + get per key; backends with a server-side scan override
        it with ONE round trip (the KV backend does — a watch poll
        must never cost more wire ops than the plain read it gates)."""
        out = {}
        for key in self.list(prefix):
            got = self.get(key)
            if got is not None:
                out[key] = got
        return out

    def lease(self, key, ttl, payload):
        lease = Lease(self, key, ttl)
        lease.refresh(payload)
        return lease

    def watch(self, prefix=''):
        return Watch(self, prefix)

    def ensure_prefix(self, prefix):
        """Scaffold a key prefix where that means something (a POSIX
        directory an operator will ``ls``); a no-op on KV backends."""

    def close(self):
        pass


def default_retry_policy():
    """Default per-op policy: small, bounded, jittered — a coordination
    op sits inside supervisor poll loops, so the whole budget must stay
    in the seconds range (give up loudly rather than stall a barrier).
    """
    from kfac_pytorch_tpu.resilience.retry import RetryPolicy
    return RetryPolicy(attempts=5, base_delay=0.1, max_delay=2.0,
                       multiplier=2.0, jitter=0.5,
                       retry_on=(CoordTimeout,))


class RetryingBackend(CoordBackend):
    """Per-op bounded retry (backoff + jitter) around any backend.

    Every retry bumps the process-global ``coord_retries`` counter and
    accumulates the slept seconds (``stats()['wait_s']``); exhausting
    the budget logs the machine-greppable give-up form and raises
    :class:`CoordGiveUp` so the caller can exit
    :data:`~kfac_pytorch_tpu.coord.RC_COORD_LOST` instead of wedging.
    CAS conflicts are answers, not failures — they never retry.
    """

    def __init__(self, inner, *, policy=None, clock=None, rng=None,
                 log=None):
        import random

        from kfac_pytorch_tpu.resilience.retry import REAL_CLOCK
        self.inner = inner
        self.policy = policy or default_retry_policy()
        self.clock = clock or REAL_CLOCK
        self.rng = rng or random
        self.log = log if log is not None else logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._retries = 0
        self._gave_up = 0
        self._wait_s = 0.0

    def stats(self):
        with self._lock:
            return {'retries': self._retries, 'gave_up': self._gave_up,
                    'wait_s': self._wait_s}

    def _call(self, op, key, fn):
        last = None
        for attempt in range(self.policy.attempts):
            try:
                return fn()
            except self.policy.retry_on as e:
                last = e
                if attempt == self.policy.attempts - 1:
                    break
                delay = self.policy.delay(attempt, self.rng)
                with self._lock:
                    self._retries += 1
                    self._wait_s += delay
                _res().counters.bump('coord_retries')
                self.log.warning(
                    'coord: retry %d/%d op=%s key=%s in %.2fs after: %s',
                    attempt + 1, self.policy.attempts - 1, op, key,
                    delay, e)
                self.clock.sleep(delay)
        with self._lock:
            self._gave_up += 1
        _res().counters.bump('coord_gave_ups')
        self.log.error(
            'coord: giving up op=%s key=%s after %d attempts (%s) '
            '[resilience: coord_gave_up=1]', op, key,
            self.policy.attempts, last)
        raise CoordGiveUp(
            f'coordination backend op {op} on {key!r} failed '
            f'{self.policy.attempts} times: {last}') from last

    # -- delegated ops ----------------------------------------------------

    def get(self, key):
        return self._call('get', key, lambda: self.inner.get(key))

    def put(self, key, value, *, indent=None, ttl=None):
        return self._call('put', key, lambda: self.inner.put(
            key, value, indent=indent, ttl=ttl))

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        # ONE idempotency token per logical CAS, shared by every retry
        # attempt: a timeout after the server applied the write must
        # read as success on the replay, never as a self-conflict that
        # makes the caller believe someone else moved the state
        if token is None:
            import os as _os
            token = _os.urandom(8).hex()
        return self._call('put_cas', key, lambda: self.inner.put_cas(
            key, value, expect_version, indent=indent, ttl=ttl,
            token=token))

    def delete(self, key):
        return self._call('delete', key, lambda: self.inner.delete(key))

    def delete_prefix(self, prefix):
        return self._call('delete_prefix', prefix,
                          lambda: self.inner.delete_prefix(prefix))

    def list(self, prefix=''):
        return self._call('list', prefix, lambda: self.inner.list(prefix))

    def get_many(self, prefix=''):
        return self._call('get_many', prefix,
                          lambda: self.inner.get_many(prefix))

    def get_many_versioned(self, prefix=''):
        return self._call('get_many_versioned', prefix,
                          lambda: self.inner.get_many_versioned(prefix))

    def lease(self, key, ttl, payload):
        lease = Lease(self, key, ttl)
        lease.refresh(payload)
        return lease

    def ensure_prefix(self, prefix):
        return self.inner.ensure_prefix(prefix)

    def close(self):
        self.inner.close()
