"""A non-POSIX coordination backend: a single-process etcd-style KV
server with versioned CAS and TTL leases, stdlib only.

This is the existence proof that :class:`~.base.CoordBackend` is a real
abstraction and not a file-system veneer: the pod protocols (shrink /
grow barriers, lineage fencing, heartbeat leases, the job queue's epoch
CAS) run unchanged against a store with none of POSIX's rename-atomic
semantics — what they need is exactly the six primitives, provided here
by one tiny server any pod host can reach over the same address plane
``hosts.json`` already names.

Wire protocol: newline-delimited JSON request/response pairs over a
PERSISTENT connection — the client keeps one socket per backend and
reconnects on error, so a simulated-fleet op rate costs one TCP
handshake per backend lifetime, not one per op. One-shot
connection-per-op clients (older versions, shell probes) still work:
the server answers requests until the peer closes. A dead server
presents as a broken/refused socket, which the retry layer converts
into bounded backoff and a loud give-up, never a wedge — a mid-stream
server restart costs the in-flight op one :class:`CoordTimeout` and the
CAS idempotency token makes the replay safe. Versions are a per-store
monotonic revision counter; a lease is a key with an ``expires`` wall
deadline the server enforces lazily on every read and in a periodic
sweep.

Run it standalone (``kfac-coord-serve --port 8479``) or in-process
(:class:`TcpKvServer` — the drills do). Select it per process with::

    KFAC_COORD_BACKEND=tcp KFAC_COORD_ADDR=host:8479

Every backend *root* (lease dir path, service dir path) becomes a key
namespace on the server, so co-hosted pods and tenants stay disjoint
exactly as their directories did.
"""

import argparse
import bisect
import contextlib
import json
import logging
import socket
import sys
import threading
import time

from kfac_pytorch_tpu.coord.base import (
    ANY, CoordBackend, CoordTimeout, Versioned, check_key, check_prefix)

log = logging.getLogger(__name__)

DEFAULT_PORT = 8479

#: sentinel the client sends for :data:`~.base.ANY` (JSON has no
#: object identity)
_ANY_WIRE = '__any__'


class TcpKvServer:
    """The store + listener. Thread-safe; ops are O(small-dict)."""

    def __init__(self, host='0.0.0.0', port=DEFAULT_PORT, *,
                 wall=time.time, sweep_interval=1.0):
        self._wall = wall
        self._lock = threading.Lock()
        # key -> [value, version, expires|None, last_writer_token|None]
        self._store = {}
        # sorted key index: every prefix op (get_many / list /
        # delete_prefix) walks ONE contiguous bisect range instead of
        # scanning the whole store — with thousands of co-hosted pod
        # namespaces on one server (the 10k-host fleet), a full scan
        # per heartbeat read is quadratic in fleet size
        self._keys = []
        self._rev = 0
        self._stopped = False
        self._sweep_interval = float(sweep_interval)
        self._last_sweep = 0.0
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.settimeout(0.25)
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]  # resolves port=0
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name='kfac-coord-kv')
        self._thread.start()

    # -- store ops (also usable in-process, the unit tests do) ------------

    def _expired(self, entry, now):
        return entry[2] is not None and now >= entry[2]

    def _sweep(self, now):
        if now - self._last_sweep < self._sweep_interval:
            return
        self._last_sweep = now
        for key in [k for k, e in self._store.items()
                    if self._expired(e, now)]:
            del self._store[key]
            self._index_drop(key)

    def _index_drop(self, key):
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]

    def _prefix_keys(self, prefix):
        """Keys with ``prefix``, sorted — strings sharing a prefix are
        one contiguous block in lexicographic order, so this is
        O(log n + matches), never a whole-store scan."""
        i = bisect.bisect_left(self._keys, prefix)
        out = []
        while i < len(self._keys) and self._keys[i].startswith(prefix):
            out.append(self._keys[i])
            i += 1
        return out

    def op(self, req):
        """One request dict -> one response dict."""
        kind = req.get('op')
        key = req.get('key', '')
        now = self._wall()
        with self._lock:
            self._sweep(now)
            if kind == 'get':
                e = self._store.get(key)
                if e is None or self._expired(e, now):
                    return {'ok': True, 'found': False}
                return {'ok': True, 'found': True, 'value': e[0],
                        'version': e[1]}
            if kind in ('put', 'cas'):
                expect = req.get('expect', _ANY_WIRE)
                token = req.get('token')
                e = self._store.get(key)
                if e is not None and self._expired(e, now):
                    e = None
                if kind == 'cas' and expect != _ANY_WIRE:
                    if token is not None and e is not None \
                            and e[3] == token:
                        # idempotent REPLAY: this caller's own CAS
                        # already applied (the response was lost on the
                        # wire) — answer the original success, never a
                        # self-conflict
                        return {'ok': True, 'version': e[1]}
                    if expect is None:
                        if e is not None:
                            return {'ok': True, 'conflict': True}
                    elif e is None or e[1] != expect:
                        return {'ok': True, 'conflict': True}
                self._rev += 1
                ttl = req.get('ttl')
                expires = now + float(ttl) if ttl else None
                if key not in self._store:
                    bisect.insort(self._keys, key)
                self._store[key] = [req.get('value'), self._rev,
                                    expires, token]
                return {'ok': True, 'version': self._rev}
            if kind == 'delete':
                e = self._store.pop(key, None)
                if e is not None:
                    self._index_drop(key)
                return {'ok': True,
                        'found': e is not None
                        and not self._expired(e, now)}
            if kind == 'delete_prefix':
                hit = self._prefix_keys(key)
                for k in hit:
                    del self._store[k]
                if hit:
                    i = bisect.bisect_left(self._keys, key)
                    del self._keys[i:i + len(hit)]
                return {'ok': True, 'count': len(hit)}
            if kind == 'list':
                keys = [k for k in self._prefix_keys(key)
                        if not self._expired(self._store[k], now)]
                return {'ok': True, 'keys': keys}
            if kind == 'get_many':
                live = {k: self._store[k]
                        for k in self._prefix_keys(key)
                        if not self._expired(self._store[k], now)}
                # versions ride along so a Watch poll is ONE round trip
                # (clients on an older server fall back to per-key gets)
                return {'ok': True,
                        'values': {k: e[0] for k, e in live.items()},
                        'versions': {k: e[1] for k, e in live.items()}}
            if kind == 'ping':
                return {'ok': True, 'rev': self._rev,
                        'keys': len(self._store)}
        return {'ok': False, 'error': f'unknown op {kind!r}'}

    # -- listener ----------------------------------------------------------

    def _serve(self):
        while not self._stopped:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # one thread per connection: a client that connects and
            # then stalls (a SIGKILLed host mid-request — the standing
            # drill) must not head-of-line-block every other host's
            # heartbeat publishes and barrier claims behind its recv
            # timeout
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        # request LOOP: serve newline-delimited ops until the peer
        # closes (persistent clients) or goes idle past the timeout —
        # a one-shot connection-per-op client just closes after its
        # first response and falls out on the empty recv
        with contextlib.suppress(OSError, ValueError), conn:
            conn.settimeout(30.0)
            buf = b''
            while True:
                while b'\n' not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                if self._stopped:
                    # checked AFTER the blocking recv: a closed server
                    # must never answer from its lingering store, even
                    # on connections that were already open
                    return
                line, buf = buf.split(b'\n', 1)
                if not line.strip():
                    continue
                try:
                    resp = self.op(json.loads(line.decode()))
                except Exception as e:  # noqa: BLE001 — server must live
                    resp = {'ok': False, 'error': str(e)}
                conn.sendall(json.dumps(resp).encode() + b'\n')

    def close(self):
        self._stopped = True
        with contextlib.suppress(OSError):
            self._srv.close()
        self._thread.join(timeout=2)


#: ops whose replay is harmless — a broken REUSED socket resends these
#: on a fresh connection transparently; everything else surfaces as one
#: CoordTimeout and lets the retry layer (CAS idempotency token in
#: hand) decide
_IDEMPOTENT_OPS = frozenset({'get', 'list', 'get_many', 'ping'})


class TcpKvBackend(CoordBackend):
    """Persistent-connection client: ONE socket per backend, reused
    across ops and re-established on error. ``namespace`` (the backend
    root — a lease-dir or service-dir path) prefixes every key on the
    server."""

    def __init__(self, addr, namespace, *, timeout=2.0):
        if isinstance(addr, str):
            host, port = addr.rsplit(':', 1)
            addr = (host, int(port))
        self.addr = (str(addr[0]), int(addr[1]))
        self.namespace = str(namespace).strip('/')
        if not self.namespace:
            # an empty namespace would make delete_prefix('') a
            # server-GLOBAL wipe across every pod/tenant on the store
            raise ValueError('TcpKvBackend needs a non-empty namespace '
                             '(the backend root — a lease/service dir '
                             'path)')
        self.timeout = float(timeout)
        self._sock = None
        self._lock = threading.Lock()

    def __repr__(self):
        return (f'TcpKvBackend({self.addr[0]}:{self.addr[1]}, '
                f'ns={self.namespace!r})')

    def _full(self, key):
        key = check_key(key)
        return f'{self.namespace}/{key}' if self.namespace else key

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.settimeout(self.timeout)
        return s

    def _drop_sock(self):
        s, self._sock = self._sock, None
        if s is not None:
            with contextlib.suppress(OSError):
                s.close()

    @staticmethod
    def _send_recv(s, payload):
        s.sendall(payload)
        raw = b''
        while not raw.endswith(b'\n'):
            chunk = s.recv(65536)
            if not chunk:
                raise OSError('connection closed mid-response')
            raw += chunk
        return raw

    def _request(self, req):
        payload = json.dumps(req).encode() + b'\n'
        with self._lock:
            try:
                fresh = self._sock is None
                if fresh:
                    self._sock = self._connect()
                raw = self._send_recv(self._sock, payload)
            except (OSError, ValueError) as e:
                self._drop_sock()
                # a REUSED socket can be stale (server restart, idle
                # disconnect): resend idempotent reads on a fresh
                # connection transparently; writes surface the error —
                # the op may or may not have applied, and the retry
                # layer's CAS token is the replay-safety mechanism
                if fresh or req.get('op') not in _IDEMPOTENT_OPS:
                    raise CoordTimeout(
                        f'coord kv {self.addr[0]}:{self.addr[1]} '
                        f'unreachable ({e})') from e
                try:
                    self._sock = self._connect()
                    raw = self._send_recv(self._sock, payload)
                except (OSError, ValueError) as e2:
                    self._drop_sock()
                    raise CoordTimeout(
                        f'coord kv {self.addr[0]}:{self.addr[1]} '
                        f'unreachable ({e2})') from e2
        try:
            resp = json.loads(raw.decode())
        except ValueError as e:
            raise CoordTimeout(
                f'coord kv {self.addr[0]}:{self.addr[1]} sent a '
                f'malformed response ({e})') from e
        if not resp.get('ok'):
            raise CoordTimeout(f'coord kv error: {resp.get("error")}')
        return resp

    def close(self):
        with self._lock:
            self._drop_sock()

    # -- primitives --------------------------------------------------------

    def get(self, key):
        resp = self._request({'op': 'get', 'key': self._full(key)})
        if not resp.get('found'):
            return None
        return Versioned(resp.get('value'), resp.get('version'))

    def put(self, key, value, *, indent=None, ttl=None):
        del indent  # a wire format, not a file format
        req = {'op': 'put', 'key': self._full(key), 'value': value}
        if ttl:
            req['ttl'] = float(ttl)
        return self._request(req)['version']

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        del indent
        req = {'op': 'cas', 'key': self._full(key), 'value': value,
               'expect': (_ANY_WIRE if expect_version is ANY
                          else expect_version)}
        if token is not None:
            req['token'] = str(token)
        if ttl:
            req['ttl'] = float(ttl)
        resp = self._request(req)
        if resp.get('conflict'):
            return None
        return resp['version']

    def delete(self, key):
        return bool(self._request({'op': 'delete',
                                   'key': self._full(key)}).get('found'))

    def delete_prefix(self, prefix):
        return int(self._request(
            {'op': 'delete_prefix',
             'key': self._full_prefix(prefix)}).get('count', 0))

    def _full_prefix(self, prefix):
        prefix = check_prefix(prefix)
        return f'{self.namespace}/{prefix}'

    def _strip(self, key):
        ns = f'{self.namespace}/' if self.namespace else ''
        return key[len(ns):] if ns and key.startswith(ns) else key

    def list(self, prefix=''):
        resp = self._request({'op': 'list',
                              'key': self._full_prefix(prefix)})
        return [self._strip(k) for k in resp.get('keys', ())]

    def get_many(self, prefix=''):
        resp = self._request({'op': 'get_many',
                              'key': self._full_prefix(prefix)})
        return {self._strip(k): v
                for k, v in (resp.get('values') or {}).items()}

    def get_many_versioned(self, prefix=''):
        """One round trip: the server's get_many carries versions, so a
        Watch poll never multiplies wire ops N+1-fold over the plain
        scan it gates. An older server without the versions field
        degrades to the derived per-key path."""
        resp = self._request({'op': 'get_many',
                              'key': self._full_prefix(prefix)})
        versions = resp.get('versions')
        if versions is None:
            return super().get_many_versioned(prefix)
        values = resp.get('values') or {}
        return {self._strip(k): Versioned(values.get(k), v)
                for k, v in versions.items() if k in values}

    def ping(self):
        return self._request({'op': 'ping'})


def main(argv=None):
    p = argparse.ArgumentParser(
        prog='kfac-coord-serve',
        description='Run the stdlib etcd-style coordination KV server '
                    'pods/services point KFAC_COORD_ADDR at '
                    '(KFAC_COORD_BACKEND=tcp).')
    p.add_argument('--host', default='0.0.0.0')
    p.add_argument('--port', type=int, default=DEFAULT_PORT)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format='%(asctime)s %(message)s')
    srv = TcpKvServer(args.host, args.port)
    log.info('coord kv server listening on %s:%d', args.host, srv.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
    return 0


if __name__ == '__main__':
    sys.exit(main())
