"""Pluggable coordination backends for the fleet protocols.

Every fleet-level protocol in this repo — the shrink/grow membership
barriers, quorum + lineage fencing (``resilience.elastic``), heartbeat
leases (``resilience.heartbeat``), the durable job queue and the
``kfac-serve`` capacity pool (``service/``) — used to bottom out on one
shared POSIX lease directory of atomic-rename JSON files. This package
names the primitives those protocols actually need
(:class:`~.base.CoordBackend`: get / put / versioned CAS / delete /
prefix list / TTL lease / watch) and ships two implementations:

- :class:`~.posix.PosixDirBackend` — the default; byte-compatible with
  the existing protocol files, so every drill, incident grammar and
  ``kfac-obs`` timeline works unchanged.
- :class:`~.tcpkv.TcpKvBackend` — a single-process etcd-style KV server
  (``kfac-coord-serve``) with versioned CAS and server-enforced TTL
  leases; no shared filesystem anywhere in the coordination plane.
- :class:`~.replicated.ReplicatedKvBackend` — quorum reads/writes over
  3 KV replicas with a monotonic per-key replication revision as the
  fence: one replica down or partitioned is invisible to callers, and
  only true quorum loss degrades to the loud ``RC_COORD_LOST``.

Plus the two wrappers that make the plane *testable* and *survivable*:
:class:`~.chaos.ChaosBackend` (seeded ``KFAC_FAULT_COORD_*`` fault
injection — the ``chaos_net`` idiom one layer down) and
:class:`~.base.RetryingBackend` (bounded per-op backoff + jitter with a
loud give-up). Selection is one env pair::

    KFAC_COORD_BACKEND=posix          # default: the shared lease dir
    KFAC_COORD_BACKEND=tcp KFAC_COORD_ADDR=host:8479
    KFAC_COORD_BACKEND=replicated KFAC_COORD_ADDRS=h0:8479,h1:8479,h2:8479

:func:`backend_from_env` builds the full stack (base backend → chaos
wrapper when armed → retry wrapper) for a given *root* (a lease-dir or
service-dir path — on the KV server it becomes the key namespace, so
disjoint directories stay disjoint stores).
"""

import dataclasses
import os

from kfac_pytorch_tpu.coord.base import (
    ANY, CoordBackend, CoordError, CoordGiveUp, CoordTimeout, Lease,
    RetryingBackend, Versioned, Watch, default_retry_policy)
from kfac_pytorch_tpu.coord.chaos import (
    COORD_ENVS, ChaosBackend, CoordFaultConfig)
from kfac_pytorch_tpu.coord.chaos import from_env as chaos_from_env
from kfac_pytorch_tpu.coord.chaos import maybe_wrap as maybe_wrap_chaos
from kfac_pytorch_tpu.coord.posix import PosixDirBackend
from kfac_pytorch_tpu.coord.replicated import ReplicatedKvBackend
from kfac_pytorch_tpu.coord.tcpkv import (
    DEFAULT_PORT, TcpKvBackend, TcpKvServer)

#: backend selection env contract (exported by launchers / the service
#: scheduler to every supervisor and trainer of a run)
ENV_BACKEND = 'KFAC_COORD_BACKEND'
ENV_ADDR = 'KFAC_COORD_ADDR'
ENV_ADDRS = 'KFAC_COORD_ADDRS'

#: "the coordination plane is gone": exit code of a supervisor or
#: scheduler whose backend ops exhausted their retry budget
#: (:class:`CoordGiveUp`). Distinct from the trainer-protocol codes
#: (113/114/115) and the membership verdicts (116/117): the operator's
#: reaction is to check the coordination backend (is the KV server up?
#: is the lease filesystem mounted?), not the pod.
RC_COORD_LOST = 118


def backend_from_env(root, *, retry=True, policy=None, chaos=True,
                     env=None, clock=None, rng=None):
    """Build the coordination stack for ``root``.

    ``root`` is the protocol namespace — the lease-dir path for a pod,
    the service-dir path for the scheduler. ``posix`` (default) maps it
    onto that directory; ``tcp`` namespaces keys under it on the server
    at ``KFAC_COORD_ADDR``. ``retry=False`` skips the retry wrapper
    (heartbeat transports want raw misses, not backoff stalls inside
    the liveness path); ``chaos=False`` skips fault injection (reserved
    for backends that must stay truthful, e.g. forensics writers).
    """
    e = os.environ if env is None else env
    kind = (e.get(ENV_BACKEND) or 'posix').strip().lower()
    if kind in ('posix', 'file', ''):
        backend = PosixDirBackend(root)
    elif kind == 'tcp':
        addr = (e.get(ENV_ADDR) or '').strip()
        if not addr:
            raise ValueError(
                f'{ENV_BACKEND}=tcp needs {ENV_ADDR} ("host:port" of a '
                'kfac-coord-serve KV server)')
        backend = TcpKvBackend(addr, namespace=str(root))
    elif kind == 'replicated':
        addrs = [a.strip()
                 for a in (e.get(ENV_ADDRS) or '').replace(';', ',')
                 .split(',') if a.strip()]
        if len(addrs) < 2:
            raise ValueError(
                f'{ENV_BACKEND}=replicated needs {ENV_ADDRS} '
                '(comma-separated "host:port" of at least 2 — normally '
                '3 — kfac-coord-serve replicas)')
        cfg = chaos_from_env(env=e) if chaos else None
        replicas = []
        for i, addr in enumerate(addrs):
            rep = TcpKvBackend(addr, namespace=str(root))
            if cfg is not None and cfg.any_chaos:
                # per-replica seed offset: the same seed on every
                # replica would fault all of them in lockstep, which is
                # exactly the correlated failure a quorum cannot absorb
                # — the drill must make replicas DISAGREE
                rep = ChaosBackend(
                    rep, dataclasses.replace(cfg, seed=cfg.seed + i))
            replicas.append(rep)
        # thread the injected clock (an object with .monotonic, the
        # RetryingBackend convention) down to the quorum layer's
        # down-replica cooldown — under a simulated clock a cooldown
        # measured in real seconds would outlive a whole outage window
        backend = ReplicatedKvBackend(
            replicas,
            clock=clock.monotonic if clock is not None else None)
        chaos = False  # injected per-replica above, not on the merge
    else:
        raise ValueError(f'{ENV_BACKEND} must be "posix", "tcp" or '
                         f'"replicated", got {kind!r}')
    if chaos:
        backend = maybe_wrap_chaos(backend)
    if retry:
        backend = RetryingBackend(backend, policy=policy, clock=clock,
                                  rng=rng)
    return backend


#: short alias, mirroring ``chaos_net.from_env`` / ``faults.from_env``
from_env = backend_from_env

__all__ = [
    'ANY', 'CoordBackend', 'CoordError', 'CoordGiveUp', 'CoordTimeout',
    'Lease', 'Versioned', 'Watch', 'RetryingBackend',
    'default_retry_policy', 'PosixDirBackend', 'TcpKvBackend',
    'TcpKvServer', 'ReplicatedKvBackend', 'DEFAULT_PORT',
    'ChaosBackend', 'CoordFaultConfig', 'COORD_ENVS', 'chaos_from_env',
    'maybe_wrap_chaos', 'ENV_BACKEND', 'ENV_ADDR', 'ENV_ADDRS',
    'RC_COORD_LOST', 'backend_from_env', 'from_env',
]
