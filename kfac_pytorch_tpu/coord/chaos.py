"""Deterministic fault injection at the coordination-backend level —
the ``chaos_net`` idiom applied one layer down.

``chaos_net`` makes the pod's *message* plane misbehave (heartbeat
deliveries dropped, delayed, partitioned). What it cannot exercise is
the *coordination* plane itself failing: the lease store timing out,
returning stale or torn state, spuriously rejecting a CAS, or expiring
a lease its owner was still refreshing. :class:`ChaosBackend` wraps any
:class:`~.base.CoordBackend` and injects exactly those, with every
decision a pure SHA-256 function of ``(seed, op, key, attempt)`` —
identical env + identical op sequence ⇒ identical fault schedule, which
is what the determinism tests pin.

Env contract (``KFAC_FAULT_COORD_*``, registered in ``faults.py``'s
STRICT ``from_env`` so a typo'd drill fails loudly at build time):

  KFAC_FAULT_COORD_SEED      int; presence arms the chaos layer
  KFAC_FAULT_COORD_FAIL      P(an op raises CoordTimeout)        [0, 1]
  KFAC_FAULT_COORD_TORN      P(a get returns None — a torn read)
  KFAC_FAULT_COORD_STALE     P(a get/get_many returns the PREVIOUS
                             value this process saw for the key)
  KFAC_FAULT_COORD_CAS       P(a put_cas reports a spurious conflict
                             WITHOUT applying — the caller must re-read
                             and re-derive, the CAS contract)
  KFAC_FAULT_COORD_LEASE_EXPIRE
                             P(a lease publish is silently dropped —
                             the premature-expiry drill: the key stops
                             advancing and readers declare its owner
                             dead on schedule)
  KFAC_FAULT_COORD_WINDOWS   unavailability windows "10:40;90:95"
                             relative to T0 — every op inside a window
                             raises CoordTimeout (the backend-outage
                             drill the RetryPolicy must ride out or
                             give up on loudly)
  KFAC_FAULT_COORD_T0        wall-clock base of the windows (default:
                             config load time)

Faults apply at the WRAPPER, so both backends (and any future one) are
drillable identically; the retry layer sits OUTSIDE the chaos wrapper,
which is the point — retries are the system under test.
"""

import collections
import dataclasses
import hashlib
import os
import time
from typing import Optional, Tuple

from kfac_pytorch_tpu.coord.base import CoordBackend, CoordTimeout

ENV_COORD_SEED = 'KFAC_FAULT_COORD_SEED'
ENV_COORD_FAIL = 'KFAC_FAULT_COORD_FAIL'
ENV_COORD_TORN = 'KFAC_FAULT_COORD_TORN'
ENV_COORD_STALE = 'KFAC_FAULT_COORD_STALE'
ENV_COORD_CAS = 'KFAC_FAULT_COORD_CAS'
ENV_COORD_LEASE = 'KFAC_FAULT_COORD_LEASE_EXPIRE'
ENV_COORD_WINDOWS = 'KFAC_FAULT_COORD_WINDOWS'
ENV_COORD_T0 = 'KFAC_FAULT_COORD_T0'

COORD_ENVS = frozenset({
    ENV_COORD_SEED, ENV_COORD_FAIL, ENV_COORD_TORN, ENV_COORD_STALE,
    ENV_COORD_CAS, ENV_COORD_LEASE, ENV_COORD_WINDOWS, ENV_COORD_T0,
})


def parse_windows(spec, env=ENV_COORD_WINDOWS):
    """``"10:40;90:95"`` -> ((10.0, 40.0), (90.0, 95.0))."""
    out = []
    for part in str(spec).split(';'):
        part = part.strip()
        if not part:
            continue
        try:
            lo, hi = part.split(':', 1)
            start, end = float(lo), float(hi)
        except ValueError:
            raise ValueError(f'{env}: malformed window {part!r}; '
                             'expected "start:end" seconds') from None
        if end <= start:
            raise ValueError(f'{env}: window {part!r} ends before it '
                             'starts')
        out.append((start, end))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class CoordFaultConfig:
    seed: int = 0
    fail: float = 0.0
    torn: float = 0.0
    stale: float = 0.0
    cas: float = 0.0
    lease_expire: float = 0.0
    windows: Tuple[Tuple[float, float], ...] = ()
    t0: float = 0.0

    @property
    def any_chaos(self):
        return bool(self.fail or self.torn or self.stale or self.cas
                    or self.lease_expire or self.windows)

    def unavailable(self, wall):
        rel = wall - self.t0
        return any(lo <= rel < hi for lo, hi in self.windows)


def _prob_env(env, e):
    raw = e.get(env)
    if not raw:
        return 0.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f'{env} must be a probability in [0, 1], '
                         f'got {raw!r}') from None
    if not 0.0 <= v <= 1.0:
        raise ValueError(f'{env} must be in [0, 1], got {v}')
    return v


def from_env(env=None):
    """Snapshot the coordination-fault environment, or None when no
    ``KFAC_FAULT_COORD_*`` variable is set. STRICT like
    ``faults.from_env`` (which delegates validation here)."""
    e = os.environ if env is None else env
    if not any(k in e for k in COORD_ENVS):
        return None
    raw_seed = e.get(ENV_COORD_SEED, '0')
    try:
        seed = int(raw_seed)
    except ValueError:
        raise ValueError(f'{ENV_COORD_SEED} must be an integer, '
                         f'got {raw_seed!r}') from None
    raw_t0 = e.get(ENV_COORD_T0)
    try:
        t0 = float(raw_t0) if raw_t0 else time.time()
    except ValueError:
        raise ValueError(f'{ENV_COORD_T0} must be a wall timestamp, '
                         f'got {raw_t0!r}') from None
    spec = e.get(ENV_COORD_WINDOWS)
    return CoordFaultConfig(
        seed=seed,
        fail=_prob_env(ENV_COORD_FAIL, e),
        torn=_prob_env(ENV_COORD_TORN, e),
        stale=_prob_env(ENV_COORD_STALE, e),
        cas=_prob_env(ENV_COORD_CAS, e),
        lease_expire=_prob_env(ENV_COORD_LEASE, e),
        windows=parse_windows(spec) if spec else (),
        t0=t0)


def _u(cfg, op, key, attempt, lane):
    """One uniform draw in [0, 1): a pure function of
    ``(seed, op, key, attempt)`` per fault lane — the determinism
    contract (SHA-256, stable across runs and interpreters)."""
    digest = hashlib.sha256(
        f'{cfg.seed}:{op}:{key}:{attempt}'.encode()).digest()
    i = lane * 8
    return int.from_bytes(digest[i:i + 8], 'big') / 2 ** 64


class ChaosBackend(CoordBackend):
    """Wrap a backend; inject the seeded fault schedule. ``trace``
    records every injected fault as ``(kind, op, key, attempt)`` —
    bounded, like the ChaosTransport delivery trace."""

    def __init__(self, inner, cfg, *, wall=time.time):
        self.inner = inner
        self.cfg = cfg
        self._wall = wall
        self._attempts = {}          # (op, key) -> count
        self._last_seen = {}         # key -> previous Versioned (stale)
        self._last_vals = {}         # key -> previous value (get_many)
        self.trace = collections.deque(maxlen=65536)
        self.counts = collections.Counter()

    def __repr__(self):
        return f'ChaosBackend({self.inner!r})'

    def _attempt(self, op, key):
        if len(self._attempts) > 65536:
            # bounded backstop (delete-op counters survive eviction):
            # keep the most recent half, insertion-ordered
            self._attempts = dict(
                list(self._attempts.items())[-32768:])
        k = (op, str(key))
        self._attempts[k] = n = self._attempts.get(k, 0) + 1
        return n

    def _inject(self, kind, op, key, attempt):
        self.counts[kind] += 1
        self.trace.append((kind, op, str(key), attempt))

    def _gate(self, op, key):
        """The fail/window lane shared by every op; returns the attempt
        index for the op-specific lanes."""
        attempt = self._attempt(op, key)
        if self.cfg.windows and self.cfg.unavailable(self._wall()):
            self._inject('window', op, key, attempt)
            raise CoordTimeout(
                f'injected coord outage window (op={op} key={key})')
        if self.cfg.fail and _u(self.cfg, op, key, attempt, 0) \
                < self.cfg.fail:
            self._inject('fail', op, key, attempt)
            raise CoordTimeout(
                f'injected coord op failure (op={op} key={key} '
                f'attempt={attempt})')
        return attempt

    # -- reads -------------------------------------------------------------

    def get(self, key):
        attempt = self._gate('get', key)
        if self.cfg.torn and _u(self.cfg, 'get', key, attempt, 1) \
                < self.cfg.torn:
            self._inject('torn', 'get', key, attempt)
            return None
        got = self.inner.get(key)
        if got is not None:
            prev = self._last_seen.get(key)
            if (prev is not None and prev.version != got.version
                    and self.cfg.stale
                    and _u(self.cfg, 'get', key, attempt, 2)
                    < self.cfg.stale):
                self._inject('stale', 'get', key, attempt)
                return prev
            self._last_seen[key] = got
        return got

    def list(self, prefix=''):
        self._gate('list', prefix)
        return self.inner.list(prefix)

    def get_many(self, prefix=''):
        # ONE inner round trip (a per-key fan-out would multiply wire
        # ops N+1-fold on the KV backend), torn/stale lanes applied per
        # key on the result — same coverage, same determinism contract
        self._gate('get_many', prefix)
        raw = self.inner.get_many(prefix)
        out = {}
        for key in sorted(raw):
            value = raw[key]
            attempt = self._attempt('get', key)
            if self.cfg.torn and _u(self.cfg, 'get', key, attempt, 1) \
                    < self.cfg.torn:
                self._inject('torn', 'get', key, attempt)
                continue
            prev = self._last_vals.get(key)
            if (prev is not None and prev != value and self.cfg.stale
                    and _u(self.cfg, 'get', key, attempt, 2)
                    < self.cfg.stale):
                self._inject('stale', 'get', key, attempt)
                out[key] = prev
                continue
            self._last_vals[key] = value
            out[key] = value
        return out

    # -- writes ------------------------------------------------------------

    def put(self, key, value, *, indent=None, ttl=None):
        attempt = self._gate('put', key)
        if (ttl and self.cfg.lease_expire
                and _u(self.cfg, 'lease', key, attempt, 3)
                < self.cfg.lease_expire):
            # premature lease expiry: the publish silently vanishes —
            # the key stops advancing exactly as if the server dropped
            # the lease early, and readers react on their deadline
            self._inject('lease_expire', 'put', key, attempt)
            return f'chaos-dropped-{attempt}'
        return self.inner.put(key, value, indent=indent, ttl=ttl)

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        attempt = self._gate('put_cas', key)
        if self.cfg.cas and _u(self.cfg, 'put_cas', key, attempt, 1) \
                < self.cfg.cas:
            self._inject('cas_conflict', 'put_cas', key, attempt)
            return None  # reported conflict, nothing applied
        return self.inner.put_cas(key, value, expect_version,
                                  indent=indent, ttl=ttl, token=token)

    def delete(self, key):
        self._gate('delete', key)
        self._evict(key)
        return self.inner.delete(key)

    def delete_prefix(self, prefix):
        self._gate('delete_prefix', prefix)
        for key in [k for k in self._last_vals
                    if k.startswith(str(prefix))]:
            self._evict(key)
        for key in {k for _op, k in self._attempts
                    if k.startswith(str(prefix))}:
            self._evict(key)
        return self.inner.delete_prefix(prefix)

    def _evict(self, key):
        """Deleted keys drop their fault-lane state: every spool entry
        is a fresh unique key, and a long-running chaos-armed service
        must not grow these maps monotonically (the trace deque is
        bounded; these would not be). The delete ops' own counters are
        KEPT — resetting them mid-retry would redraw attempt 1 forever
        and turn one injected delete failure into a permanent one."""
        key = str(key)
        self._last_seen.pop(key, None)
        self._last_vals.pop(key, None)
        for pair in [p for p in self._attempts
                     if p[1] == key
                     and p[0] not in ('delete', 'delete_prefix')]:
            del self._attempts[pair]

    def ensure_prefix(self, prefix):
        return self.inner.ensure_prefix(prefix)

    def close(self):
        self.inner.close()


def maybe_wrap(backend, cfg=None):
    """Wrap ``backend`` in a :class:`ChaosBackend` when the chaos env
    is armed (or an explicit ``cfg`` is given); otherwise return it
    untouched — the one-liner every backend construction site uses."""
    if cfg is None:
        cfg = from_env()
    if cfg is None or not cfg.any_chaos:
        return backend
    return ChaosBackend(backend, cfg)
