"""Quorum-replicated coordination: N (normally 3) independent
:class:`~.tcpkv.TcpKvServer` replicas behind one logical
:class:`~.base.CoordBackend`, so the coordination plane itself stops
being the single point of failure ROADMAP item 4 names.

Replication fence — the PR-7 lineage-epoch trick applied per key:
every write carries a monotonic *replication revision* ``r`` (plus a
writer nonce ``n`` for same-revision tiebreaks) inside the stored
envelope, and that ``r`` is the version the contract exposes::

    {'r': 7, 'n': '<writer-nonce>', 'v': <the JSON value>}       # value
    {'r': 8, 'n': '<writer-nonce>', 'tomb': True}                # delete

- **Writes** read a quorum to learn the highest ``r`` seen anywhere,
  write ``r + 1`` to every answering replica, and succeed only on a
  quorum of acks — so any two committed writes are ordered by ``r``
  and any read quorum overlaps every committed write.
- **Reads** take the majority answer: the envelope with the highest
  ``(r, n)`` wins, unless a *majority* of answering replicas say the
  key is absent (which is how a lagging replica's resurrected value
  loses after the tombstone TTL). Lagging replicas are repaired
  read-through — pushed the winning envelope with a CAS against the
  stale native version, so a repair can never clobber a newer write.
- **CAS** checks ``expect_version`` against the winning ``r`` and then
  writes per-replica with a CAS against each replica's *native* version
  from the read phase, so two racing CAS callers cannot both reach a
  quorum; the retry layer's idempotency token rides in the envelope
  (``tok``) and a replayed attempt that finds its own token winning
  just completes the write instead of self-conflicting.
- **Deletes** are quorum-written tombstones with a server-enforced TTL
  (:data:`TOMBSTONE_TTL`): a replica partitioned for the whole
  tombstone lifetime can in principle resurrect a deleted key until
  the next read repairs it — the protocols above (create-only claims,
  epoch CAS) are insensitive to this, and the window is one scan wide.

One replica down or partitioned is *invisible* to callers (reads and
writes still reach a quorum; the replica is repaired read-through when
it returns). Losing the quorum raises :class:`~.base.CoordTimeout`
per-op, which the retry wrapper converts into the existing loud
``CoordGiveUp`` → ``RC_COORD_LOST=118`` — exactly the single-server
failure story, just requiring two simultaneous failures to trigger.

Selection (``backend_from_env``)::

    KFAC_COORD_BACKEND=replicated \
    KFAC_COORD_ADDRS=host0:8479,host1:8479,host2:8479

``KFAC_FAULT_COORD_*`` chaos injects *per replica* (seed offset by the
replica index, so the drills exercise disagreeing replicas instead of
faulting all three in lockstep).

Replica failures are first-class incident events (the
``kfac-obs``/incident grammar): ``replica_down`` when a replica stops
answering, ``replica_repair`` for every read-through repair,
``quorum_degraded`` when the pool first drops below full strength.
"""

import contextlib
import collections
import logging
import os
import threading
import time

from kfac_pytorch_tpu.coord.base import (
    ANY, CoordBackend, CoordTimeout, Versioned, check_key, check_prefix)


def _res():
    # lazy: mirrors base.py — coord is imported by resilience submodules
    from kfac_pytorch_tpu import resilience
    return resilience


#: server-enforced lifetime of a delete tombstone. Long enough that a
#: briefly-lagging replica is repaired well before the majority forgets
#: the delete; short enough that tombstones never accumulate.
TOMBSTONE_TTL = 60.0

#: after a replica fails an op, skip it for this long before probing
#: again — a dead TCP replica must cost one connect timeout per
#: cooldown, not one per op (heartbeat scans run several ops a second).
DOWN_COOLDOWN = 2.0


class ReplicatedKvBackend(CoordBackend):
    """Quorum reads/writes over ``replicas`` (CoordBackend instances,
    normally :class:`~.tcpkv.TcpKvBackend` — anything with the same
    contract works, which is what the fleet simulator exploits)."""

    def __init__(self, replicas, *, quorum=None, names=None,
                 down_cooldown=DOWN_COOLDOWN, clock=time.monotonic,
                 log=None):
        self.replicas = list(replicas)
        n = len(self.replicas)
        if n < 2:
            raise ValueError('ReplicatedKvBackend needs at least 2 '
                             f'replicas (got {n}); one replica is just '
                             'the tcp backend with extra steps')
        self.quorum = int(quorum) if quorum else n // 2 + 1
        if not 0 < self.quorum <= n:
            raise ValueError(f'quorum {self.quorum} out of range for '
                             f'{n} replicas')
        if names is not None:
            self._names = [str(x) for x in names]
        else:
            self._names = []
            for i, rep in enumerate(self.replicas):
                addr = getattr(rep, 'addr', None) \
                    or getattr(getattr(rep, 'inner', None), 'addr', None)
                self._names.append(f'{addr[0]}:{addr[1]}'
                                   if addr else f'replica{i}')
        self.down_cooldown = float(down_cooldown)
        self._clock = clock if clock is not None else time.monotonic
        self.log = log if log is not None else logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._down_until = [0.0] * n
        self._up = [True] * n
        self._degraded = False
        self._nonce_ctr = 0
        # nonces only break (r, n) ties between concurrent writers —
        # they never appear in any trace, so randomness here does not
        # touch the simulator's determinism contract
        self._instance = os.urandom(4).hex()
        self.counts = collections.Counter()

    def __repr__(self):
        return (f'ReplicatedKvBackend({", ".join(self._names)}, '
                f'quorum={self.quorum})')

    # -- replica pool state ------------------------------------------------

    def _next_nonce(self):
        with self._lock:
            self._nonce_ctr += 1
            return f'{self._instance}-{self._nonce_ctr:08d}'

    def _mark_down(self, i, exc):
        with self._lock:
            self._down_until[i] = self._clock() + self.down_cooldown
            was_up = self._up[i]
            self._up[i] = False
            reachable = sum(self._up)
        if was_up:
            self.counts['replica_down'] += 1
            _res().counters.bump('replica_down')
            self.log.error(
                'coord-replicated: replica %s down — %s (%d/%d replicas '
                'reachable) [resilience: replica_down=1]',
                self._names[i], exc, reachable, len(self.replicas))

    def _mark_up(self, i):
        with self._lock:
            was_up = self._up[i]
            self._up[i] = True
            self._down_until[i] = 0.0
        if not was_up:
            # narration, not an incident event: the greppable story is
            # replica_down -> replica_repair; this line just marks when
            # the probe started answering again
            self.log.info('coord-replicated: contact restored with %s '
                          '(read-through repair will catch it up)',
                          self._names[i])

    def _note_degraded(self, responders):
        total = len(self.replicas)
        with self._lock:
            if responders >= total:
                self._degraded = False
                return
            if self._degraded:
                return
            self._degraded = True
        self.counts['quorum_degraded'] += 1
        _res().counters.bump('quorum_degraded')
        self.log.warning(
            'coord-replicated: quorum degraded — %d of %d replicas '
            'answering (quorum %d) [resilience: quorum_degraded=1]',
            responders, total, self.quorum)

    def _fan(self, op, key, fn):
        """``fn(replica)`` on every replica not in down-cooldown;
        returns ``{index: result}`` for the ones that answered. Raises
        :class:`CoordTimeout` (the retryable verdict) below quorum."""
        now = self._clock()
        results = {}
        for i, rep in enumerate(self.replicas):
            if now < self._down_until[i]:
                continue
            try:
                results[i] = fn(rep)
            except (OSError, ValueError) as e:
                self._mark_down(i, e)
            else:
                self._mark_up(i)
        if len(results) < self.quorum:
            raise CoordTimeout(
                f'coord-replicated: quorum lost — {len(results)} of '
                f'{len(self.replicas)} replicas answered op={op} '
                f'key={key!r} (need {self.quorum})')
        self._note_degraded(len(results))
        return results

    # -- envelopes ---------------------------------------------------------

    @staticmethod
    def _env(got):
        """The replication envelope out of one replica's answer, or
        None for absent / not-an-envelope (a foreign value in the
        namespace is treated as absent — replicated namespaces must be
        replicated-only)."""
        if got is None:
            return None
        value = got.value
        if isinstance(value, dict) and isinstance(value.get('r'), int):
            return value
        return None

    @staticmethod
    def _rank(env):
        return (env['r'], str(env.get('n', '')))

    def _merge(self, answers):
        """``(winner_env | None, absent_majority, max_r)`` over
        ``{index: Versioned | None}``. ``absent_majority`` is judged
        against the ABSOLUTE quorum, not the responder count: a
        committed write lives on >= quorum replicas, so it can never be
        out-voted by absence — only an uncommitted or resurrected
        value can."""
        winner = None
        absent = 0
        max_r = 0
        for got in answers.values():
            env = self._env(got)
            if env is None:
                absent += 1
                continue
            max_r = max(max_r, env['r'])
            if winner is None or self._rank(env) > self._rank(winner):
                winner = env
        return winner, absent >= self.quorum, max_r

    def _repair(self, key, winner, answers, *, ttl=None):
        """Push ``winner`` to every answering replica that disagrees,
        CAS'd against the stale native version read — a repair can lose
        to a concurrent newer write but never clobber one. Returns how
        many replicas now carry ``winner`` (carriers + repaired)."""
        if ttl is None:
            ttl = TOMBSTONE_TTL if winner.get('tomb') else winner.get('t')
        carriers = 0
        for i, got in answers.items():
            env = self._env(got)
            if env is not None and self._rank(env) == self._rank(winner):
                carriers += 1
                continue
            expect = None if got is None else got.version
            with contextlib.suppress(OSError, ValueError):
                if self.replicas[i].put_cas(key, winner, expect,
                                            ttl=ttl) is not None:
                    carriers += 1
                    self.counts['replica_repair'] += 1
                    _res().counters.bump('replica_repair')
                    self.log.info(
                        'coord-replicated: replica %s repaired key=%s '
                        'rrev=%d [resilience: replica_repair=1]',
                        self._names[i], key, winner['r'])
        return carriers

    def _tombstone(self, max_r):
        return {'r': max_r + 1, 'n': self._next_nonce(), 'tomb': True}

    # -- primitives --------------------------------------------------------

    def get(self, key):
        check_key(key)
        answers = self._fan('get', key, lambda r: r.get(key))
        winner, absent_maj, max_r = self._merge(answers)
        if winner is None:
            return None
        if absent_maj:
            # resurrection: a majority forgot this key (tombstone TTL
            # elapsed) while a lagging replica still holds a value —
            # re-tombstone the straggler instead of believing it
            self._repair(key, self._tombstone(max_r), answers)
            return None
        self._repair(key, winner, answers)
        if winner.get('tomb'):
            return None
        return Versioned(winner.get('v'), winner['r'])

    def put(self, key, value, *, indent=None, ttl=None):
        del indent  # a wire format, not a file format
        check_key(key)
        answers = self._fan('put', key, lambda r: r.get(key))
        _w, _a, max_r = self._merge(answers)
        env = {'r': max_r + 1, 'n': self._next_nonce(), 'v': value}
        if ttl:
            env['t'] = float(ttl)
        acks = 0
        for i in answers:
            try:
                self.replicas[i].put(key, env, ttl=ttl)
            except (OSError, ValueError) as e:
                self._mark_down(i, e)
            else:
                acks += 1
        if acks < self.quorum:
            # retry-safe: the retry re-reads, sees this partial write's
            # r as max, and rewrites everything at r + 1
            raise CoordTimeout(
                f'coord-replicated: put on {key!r} reached {acks} of '
                f'{len(self.replicas)} replicas (need {self.quorum})')
        return env['r']

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        del indent
        check_key(key)
        answers = self._fan('put_cas', key, lambda r: r.get(key))
        winner, absent_maj, max_r = self._merge(answers)
        cur = None
        if winner is not None and not absent_maj \
                and not winner.get('tomb'):
            cur = winner
        if token is not None and cur is not None \
                and cur.get('tok') == str(token):
            # REPLAY of our own CAS (the previous attempt's ack was
            # lost): complete the write instead of self-conflicting
            carriers = self._repair(key, cur, answers, ttl=ttl)
            if carriers >= self.quorum:
                return cur['r']
            raise CoordTimeout(
                f'coord-replicated: cas replay on {key!r} completed on '
                f'{carriers} replicas (need {self.quorum})')
        cur_r = None if cur is None else cur['r']
        if expect_version is None:
            if cur is not None:
                return None  # create-only, and the key exists
        elif expect_version is not ANY and cur_r != expect_version:
            return None
        env = {'r': max_r + 1, 'n': self._next_nonce(), 'v': value}
        if ttl:
            env['t'] = float(ttl)
        if token is not None:
            env['tok'] = str(token)
        acks = []
        conflicts = 0
        for i, got in answers.items():
            # CAS against each replica's NATIVE version from the read
            # phase: two racing logical CASes interleave per replica,
            # and whoever lands second on any replica conflicts there —
            # so at most one of them can reach a quorum of acks
            expect = None if got is None else got.version
            try:
                v = self.replicas[i].put_cas(
                    key, env, expect, ttl=ttl,
                    token=str(token) if token is not None else None)
            except (OSError, ValueError) as e:
                self._mark_down(i, e)
                continue
            if v is None:
                conflicts += 1
            else:
                acks.append((i, v, got))
        if len(acks) >= self.quorum:
            return env['r']
        if conflicts:
            # lost the race (or a per-replica chaos lane injected a
            # conflict): best-effort rollback of the partial writes so
            # the winner's quorum stays clean, then answer CONFLICT —
            # the caller re-reads and re-derives, the CAS contract
            for i, v, got in acks:
                with contextlib.suppress(OSError, ValueError):
                    if got is None:
                        self.replicas[i].delete(key)
                    else:
                        self.replicas[i].put_cas(key, got.value, v)
            return None
        raise CoordTimeout(
            f'coord-replicated: cas on {key!r} acked by {len(acks)} of '
            f'{len(self.replicas)} replicas (need {self.quorum})')

    def delete(self, key):
        check_key(key)
        answers = self._fan('delete', key, lambda r: r.get(key))
        winner, absent_maj, max_r = self._merge(answers)
        present = (winner is not None and not absent_maj
                   and not winner.get('tomb'))
        env = self._tombstone(max_r)
        acks = 0
        for i in answers:
            try:
                self.replicas[i].put(key, env, ttl=TOMBSTONE_TTL)
            except (OSError, ValueError) as e:
                self._mark_down(i, e)
            else:
                acks += 1
        if acks < self.quorum:
            raise CoordTimeout(
                f'coord-replicated: delete on {key!r} reached {acks} of '
                f'{len(self.replicas)} replicas (need {self.quorum})')
        return present

    def delete_prefix(self, prefix):
        check_prefix(prefix)
        count = 0
        for key in sorted(self._scan(prefix)):
            if self.delete(key):
                count += 1
        return count

    # -- scans -------------------------------------------------------------

    def _scan(self, prefix):
        """{key: winning envelope} for every LIVE key under ``prefix``
        from a quorum of replica scans; lagging replicas repaired
        in passing (this is how a returned replica catches up without
        any dedicated anti-entropy machinery — the heartbeat and queue
        scans already sweep every hot key on a cadence)."""
        answers = self._fan('get_many', prefix,
                            lambda r: r.get_many_versioned(prefix))
        keys = set()
        for d in answers.values():
            keys.update(d)
        out = {}
        for key in sorted(keys):
            per = {i: d.get(key) for i, d in answers.items()}
            winner, absent_maj, max_r = self._merge(per)
            if winner is None:
                continue
            if absent_maj:
                self._repair(key, self._tombstone(max_r), per)
                continue
            self._repair(key, winner, per)
            if not winner.get('tomb'):
                out[key] = winner
        return out

    def list(self, prefix=''):
        return sorted(self._scan(prefix))

    def get_many(self, prefix=''):
        return {k: env.get('v')
                for k, env in self._scan(prefix).items()}

    def get_many_versioned(self, prefix=''):
        return {k: Versioned(env.get('v'), env['r'])
                for k, env in self._scan(prefix).items()}

    # -- plumbing ----------------------------------------------------------

    def ping(self):
        """Per-replica liveness probe (``launch_tpu.sh`` preflight)."""
        answers = self._fan('ping', '', lambda r: r.ping())
        return {'ok': True, 'quorum': self.quorum,
                'replicas': {self._names[i]: resp
                             for i, resp in answers.items()}}

    def ensure_prefix(self, prefix):
        pass  # KV namespaces need no scaffolding

    def close(self):
        for rep in self.replicas:
            with contextlib.suppress(OSError):
                rep.close()
