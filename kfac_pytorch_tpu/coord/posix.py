"""The default backend: a shared POSIX directory of atomic-rename JSON
files — exactly the protocol every drill, incident grammar and
``kfac-obs`` timeline already reads.

Byte compatibility is the contract: ``put(key, value)`` produces the
same file, with the same bytes, at the same path, as the
``resilience.atomic_write_json`` call it replaces (``json.dump`` +
trailing newline, tmp + ``os.replace``), so a pod running half-new
half-old code during a rolling upgrade still speaks one protocol, and
every existing test that plants or inspects protocol files directly
keeps passing unchanged.

Versions are content hashes (sha256 of the file bytes, truncated):
stat-based tokens alias on filesystems with coarse mtime granularity,
and an ABA on *identical content* is harmless by construction (the CAS
would rewrite the same bytes). ``put_cas`` serializes its
check-then-replace through a per-root advisory ``flock`` (plus an
in-process lock) — best-effort, the same degrade-gracefully discipline
``write_world_stamp`` uses on lock-less filesystems.
"""

import contextlib
import hashlib
import json
import os
import shutil
import threading

from kfac_pytorch_tpu.coord.base import (
    ANY, CoordBackend, CoordTimeout, Versioned, check_key, check_prefix)

#: files the backend itself (or the atomic writer) creates that are
#: never protocol state
_SKIP_MARKERS = ('.tmp-', '.coord.lock')


def _version(raw):
    return hashlib.sha256(raw).hexdigest()[:16]


class PosixDirBackend(CoordBackend):
    """Keys map 1:1 onto files under ``root``; ``a/b.json`` is
    ``<root>/a/b.json``. TTLs are advisory (no server to expire a
    lease) — liveness readers judge sequence advance, as they always
    have."""

    def __init__(self, root):
        # the root is NOT scaffolded here: read-only attaches (e.g.
        # `kfac-serve status` on a mistyped path) must not create
        # directories as a side effect — writes create parents lazily
        self.root = str(root)
        self._lock = threading.Lock()

    def __repr__(self):
        return f'PosixDirBackend({self.root!r})'

    def _path(self, key):
        return os.path.join(self.root, *check_key(key).split('/'))

    # -- reads -------------------------------------------------------------

    def get(self, key):
        try:
            with open(self._path(key), 'rb') as f:
                raw = f.read()
            return Versioned(json.loads(raw.decode()), _version(raw))
        except (OSError, ValueError):
            # missing, unreadable, or torn mid-replace: skip this poll
            return None

    def list(self, prefix=''):
        prefix = check_prefix(prefix)
        # walk only the deepest directory the prefix fully names — a
        # claim scan over shrink-gen7/ must not stat the whole tree
        base_rel = prefix.rsplit('/', 1)[0] if '/' in prefix else ''
        start = (os.path.join(self.root, *base_rel.split('/'))
                 if base_rel else self.root)

        def _walk_error(e):
            # a MISSING prefix is an empty answer (the barrier dir not
            # created yet); any other failure (EIO/ESTALE on a network
            # filesystem) must RAISE — callers like the queue's
            # origin-dedup distinguish "empty" from "unavailable", and
            # an error read as [] would let them decide blind
            if not isinstance(e, FileNotFoundError):
                raise CoordTimeout(str(e)) from e

        out = []
        for dirpath, dirnames, filenames in os.walk(
                start, onerror=_walk_error):
            rel = os.path.relpath(dirpath, self.root)
            rel = '' if rel == '.' else rel.replace(os.sep, '/') + '/'
            # prune subtrees the prefix can never match: a 'done-' scan
            # must not descend into every shrink-gen*/trainer-gen*
            # barrier dir on a network filesystem
            dirnames[:] = [
                d for d in dirnames
                if (rel + d + '/').startswith(prefix)
                or prefix.startswith(rel + d + '/')]
            for name in filenames:
                if any(m in name for m in _SKIP_MARKERS):
                    continue
                key = rel + name
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    # -- writes ------------------------------------------------------------

    def _write(self, path, value, indent):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        raw = (json.dumps(value, indent=indent) + '\n').encode()
        tmp = f'{path}.tmp-{os.getpid()}'
        try:
            with open(tmp, 'wb') as f:
                f.write(raw)
            os.replace(tmp, path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        return _version(raw)

    def put(self, key, value, *, indent=None, ttl=None):
        del ttl  # advisory on POSIX
        return self._write(self._path(key), value, indent)

    def ensure_prefix(self, prefix):
        os.makedirs(os.path.join(
            self.root, *str(prefix).rstrip('/').split('/')),
            exist_ok=True)

    @contextlib.contextmanager
    def _cas_lock(self):
        """In-process lock + best-effort cross-process flock: the same
        degrade-gracefully discipline write_world_stamp uses."""
        with self._lock:
            fd = None
            try:
                try:
                    import fcntl
                    fd = os.open(os.path.join(self.root, '.coord.lock'),
                                 os.O_CREAT | os.O_RDWR)
                    fcntl.flock(fd, fcntl.LOCK_EX)
                except (ImportError, OSError):
                    fd = None
                yield
            finally:
                if fd is not None:
                    with contextlib.suppress(OSError):
                        os.close(fd)  # closing releases the flock

    def put_cas(self, key, value, expect_version, *, indent=None,
                ttl=None, token=None):
        del ttl, token  # local CAS cannot time out mid-apply
        path = self._path(key)
        with self._cas_lock():
            if expect_version is not ANY:
                cur = self.get(key)
                if expect_version is None:
                    if cur is not None:
                        return None
                elif cur is None or cur.version != expect_version:
                    return None
            return self._write(path, value, indent)

    def delete(self, key):
        try:
            os.remove(self._path(key))
            return True
        except FileNotFoundError:
            return False
        except OSError as e:
            raise CoordTimeout(str(e)) from e

    def delete_prefix(self, prefix):
        """Remove every key under ``prefix``; a prefix naming a whole
        directory (``shrink-gen3/``) removes the directory too — the
        ``rmtree`` idiom the barrier aborts rely on."""
        prefix = check_prefix(prefix)
        if not prefix:
            raise ValueError('delete_prefix needs a non-empty prefix '
                             '(refusing to wipe the whole namespace)')
        n = 0
        for key in self.list(prefix):
            if self.delete(key):
                n += 1
        # scrub now-empty directories the prefix names (a leftover
        # empty barrier dir reads as a live barrier to _max_grow_gen)
        dir_path = os.path.join(self.root,
                                *str(prefix).rstrip('/').split('/'))
        if os.path.isdir(dir_path) and os.path.realpath(
                dir_path) != os.path.realpath(self.root):
            shutil.rmtree(dir_path, ignore_errors=True)
        return n
