"""Checkpoint manifests: the atomic commit point of the durable
checkpoint plane.

A checkpoint epoch is COMMITTED exactly when its manifest object
exists. The writer uploads every blob first, then writes
``checkpoint-<epoch>.manifest.json`` **last** — one atomic put — so a
crash at any earlier point leaves blobs with no manifest (an
uncommitted epoch the resume scan skips), never a manifest naming
blobs that do not exist yet. The manifest records a full sha256 + size
per blob, which is what makes durability *verifiable*: ``auto_resume``
checks the bytes it restores against the manifest, and
``kfac-ckpt-verify`` scrubs whole namespaces offline, repairing from a
mirror or an older epoch by hash equality.

Manifests are lineage-stamped: the writer copies ``lineage``/``gen``/
``num_devices`` out of the ``world.json`` stamp it just wrote through
the :func:`~kfac_pytorch_tpu.utils.checkpoint.write_world_stamp` fence,
so a fenced fork's manifest is refusable by the same monotonic lineage
rule that fences the stamp itself.

jax-free: the verifier CLI runs without a training environment.
"""

import hashlib
import json
import re

FORMAT = 1

#: a committed epoch's manifest object, at the namespace top level
MANIFEST_RE = re.compile(r'^checkpoint-(\d+)\.manifest\.json$')


def manifest_key(epoch):
    return f'checkpoint-{int(epoch)}.manifest.json'


def blob_sha256(data):
    return hashlib.sha256(data).hexdigest()


def build_manifest(epoch, kind, blobs, stamp=None):
    """``blobs``: {key: bytes} or {key: (sha256_hex, size)}. ``stamp``:
    the ``world.json`` payload to copy lineage provenance from."""
    entries = {}
    for key, spec in blobs.items():
        if isinstance(spec, (bytes, bytearray, memoryview)):
            entries[str(key)] = {'sha256': blob_sha256(spec),
                                 'size': len(spec)}
        else:
            sha, size = spec
            entries[str(key)] = {'sha256': str(sha), 'size': int(size)}
    manifest = {'format': FORMAT, 'epoch': int(epoch),
                'kind': str(kind), 'blobs': entries}
    for field in ('num_devices', 'gen', 'lineage'):
        if stamp and isinstance(stamp.get(field), int):
            manifest[field] = stamp[field]
    return manifest


def encode_manifest(manifest):
    return (json.dumps(manifest, sort_keys=True, indent=1)
            + '\n').encode()


def parse_manifest(raw):
    """Decode manifest bytes; ``None`` for anything unparseable or
    structurally wrong — a torn/corrupt manifest is an UNCOMMITTED
    epoch, never a crash."""
    try:
        manifest = json.loads(bytes(raw).decode())
        if (not isinstance(manifest, dict)
                or not isinstance(manifest.get('blobs'), dict)
                or not isinstance(manifest.get('epoch'), int)):
            return None
        for spec in manifest['blobs'].values():
            if (not isinstance(spec, dict)
                    or not isinstance(spec.get('sha256'), str)
                    or not isinstance(spec.get('size'), int)):
                return None
        return manifest
    except (ValueError, UnicodeDecodeError):
        return None


def manifest_epochs(store):
    """{epoch: manifest key} for every committed epoch in the
    namespace — the resume scan's candidate set."""
    out = {}
    for key in store.list(''):
        m = MANIFEST_RE.match(key)
        if m:
            out[int(m.group(1))] = key
    return out


def read_manifest(store, epoch):
    """The parsed manifest for ``epoch``, or ``None`` (absent or
    unparseable — either way the epoch is uncommitted)."""
    blob = store.get(manifest_key(epoch))
    if blob is None:
        return None
    return parse_manifest(blob.data)


def verify_blob(store, key, spec):
    """``None`` when the stored object matches its manifest entry,
    else the reason (``'missing'`` | ``'size_mismatch'`` |
    ``'hash_mismatch'``)."""
    blob = store.get(key)
    if blob is None:
        return 'missing'
    if len(blob.data) != spec['size']:
        return 'size_mismatch'
    if blob_sha256(blob.data) != spec['sha256']:
        return 'hash_mismatch'
    return None


def verify_epoch(store, manifest):
    """[(key, reason)] for every blob of ``manifest`` that fails
    verification — empty means the epoch is intact."""
    problems = []
    for key in sorted(manifest['blobs']):
        reason = verify_blob(store, key, manifest['blobs'][key])
        if reason is not None:
            problems.append((key, reason))
    return problems
