"""The default object-store backend: a POSIX directory of
atomic-rename blob files.

Byte compatibility is the contract: ``put(key, data)`` produces the
same file, with the same bytes, at the same path, as the direct
tmp + ``os.fsync`` + ``os.replace`` write it replaces in the
checkpoint plane — so a run that flips ``KFAC_STORE_BACKEND`` between
``posix`` and unset mid-lifecycle still reads one layout, and every
existing test that plants or inspects checkpoint files directly keeps
passing unchanged.

Generations are content hashes (sha256 of the object bytes,
truncated): stat-based tokens alias on filesystems with coarse mtime
granularity, and an ABA on *identical content* is harmless by
construction (the conditional put would rewrite the same bytes).
Preconditioned puts serialize their check-then-replace through a
per-root advisory ``flock`` (plus an in-process lock) — best-effort,
the same degrade-gracefully discipline ``write_world_stamp`` uses on
lock-less filesystems.
"""

import contextlib
import hashlib
import os
import shutil
import threading

from kfac_pytorch_tpu.store.base import (
    ANY, Blob, Meta, ObjectStore, StoreTimeout, check_key, check_prefix)

#: files the backend itself creates that are never objects
_SKIP_MARKERS = ('.tmp-', '.store.lock')


def generation_of(raw):
    """The generation token for object bytes — a pure content hash, so
    every backend mints the SAME token for the SAME bytes."""
    return hashlib.sha256(raw).hexdigest()[:16]


class PosixStore(ObjectStore):
    """Keys map 1:1 onto files under ``root``; ``a/b.pkl`` is
    ``<root>/a/b.pkl``."""

    def __init__(self, root):
        # the root is NOT scaffolded here: read-only attaches (e.g.
        # `kfac-ckpt-verify` on a mistyped path) must not create
        # directories as a side effect — writes create parents lazily
        self.root = str(root)
        self._lock = threading.Lock()

    def __repr__(self):
        return f'PosixStore({self.root!r})'

    def _path(self, key):
        return os.path.join(self.root, *check_key(key).split('/'))

    # -- reads -------------------------------------------------------------

    def get(self, key):
        try:
            with open(self._path(key), 'rb') as f:
                raw = f.read()
            return Blob(raw, generation_of(raw))
        except FileNotFoundError:
            return None
        except IsADirectoryError:
            return None
        except OSError as e:
            raise StoreTimeout(str(e)) from e

    def head(self, key):
        # content-hash generations mean a head still reads the bytes;
        # on a local filesystem that is one sequential read, and it is
        # exactly the integrity scan the verifier wants anyway
        got = self.get(key)
        if got is None:
            return None
        return Meta(got.generation, len(got.data))

    def list(self, prefix=''):
        prefix = check_prefix(prefix)
        # walk only the deepest directory the prefix fully names — a
        # manifest scan over checkpoint-7/ must not stat the whole tree
        base_rel = prefix.rsplit('/', 1)[0] if '/' in prefix else ''
        start = (os.path.join(self.root, *base_rel.split('/'))
                 if base_rel else self.root)

        def _walk_error(e):
            # a MISSING prefix is an empty answer (the namespace not
            # created yet); any other failure (EIO/ESTALE on a network
            # filesystem) must RAISE — the verifier distinguishes
            # "empty" from "unavailable", and an error read as [] would
            # let it declare a whole namespace missing
            if not isinstance(e, FileNotFoundError):
                raise StoreTimeout(str(e)) from e

        out = []
        for dirpath, dirnames, filenames in os.walk(
                start, onerror=_walk_error):
            rel = os.path.relpath(dirpath, self.root)
            rel = '' if rel == '.' else rel.replace(os.sep, '/') + '/'
            # prune subtrees the prefix can never match
            dirnames[:] = [
                d for d in dirnames
                if (rel + d + '/').startswith(prefix)
                or prefix.startswith(rel + d + '/')]
            for name in filenames:
                if any(m in name for m in _SKIP_MARKERS):
                    continue
                key = rel + name
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    # -- writes ------------------------------------------------------------

    def _write(self, path, raw):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f'{path}.tmp-{os.getpid()}'
        try:
            with open(tmp, 'wb') as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        return generation_of(raw)

    @contextlib.contextmanager
    def _put_lock(self):
        """In-process lock + best-effort cross-process flock: the same
        degrade-gracefully discipline write_world_stamp uses."""
        with self._lock:
            fd = None
            try:
                try:
                    import fcntl
                    fd = os.open(os.path.join(self.root, '.store.lock'),
                                 os.O_CREAT | os.O_RDWR)
                    fcntl.flock(fd, fcntl.LOCK_EX)
                except (ImportError, OSError):
                    fd = None
                yield
            finally:
                if fd is not None:
                    with contextlib.suppress(OSError):
                        os.close(fd)  # closing releases the flock

    def put(self, key, data, *, if_generation=ANY, token=None):
        del token  # a local commit cannot lose its ack
        raw = bytes(data)
        path = self._path(key)
        if if_generation is ANY:
            try:
                return self._write(path, raw)
            except OSError as e:
                raise StoreTimeout(str(e)) from e
        with self._put_lock():
            cur = self.get(key)
            if if_generation is None:
                if cur is not None:
                    return None
            elif cur is None or cur.generation != if_generation:
                return None
            try:
                return self._write(path, raw)
            except OSError as e:
                raise StoreTimeout(str(e)) from e

    def delete(self, key):
        try:
            os.remove(self._path(key))
            return True
        except FileNotFoundError:
            return False
        except OSError as e:
            raise StoreTimeout(str(e)) from e

    def delete_prefix(self, prefix):
        """Remove every object under ``prefix``; a prefix naming a
        whole directory (``checkpoint-3/``) removes the directory too."""
        prefix = check_prefix(prefix)
        if not prefix:
            raise ValueError('delete_prefix needs a non-empty prefix '
                             '(refusing to wipe the whole namespace)')
        n = 0
        for key in self.list(prefix):
            if self.delete(key):
                n += 1
        # scrub now-empty directories the prefix names (a leftover
        # empty checkpoint dir reads as a restorable epoch to the
        # legacy downward scan)
        dir_path = os.path.join(self.root,
                                *str(prefix).rstrip('/').split('/'))
        if os.path.isdir(dir_path) and os.path.realpath(
                dir_path) != os.path.realpath(self.root):
            shutil.rmtree(dir_path, ignore_errors=True)
        return n
