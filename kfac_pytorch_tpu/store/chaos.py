"""Deterministic fault injection at the object-store level — the
``coord.chaos`` idiom applied to the durability plane.

``KFAC_FAULT_CKPT_*`` makes the checkpoint *writer* misbehave (one
injected EIO, a truncated file). What it cannot exercise is the store
*itself* failing under a correct writer: an upload dying mid-stream, a
read coming back short or stale, the backend serving 503s for a
window, or a committed put whose ack never arrives. :class:`ChaosStore`
wraps any :class:`~.base.ObjectStore` and injects exactly those, with
every decision a pure SHA-256 function of ``(seed, op, key, attempt)``
— identical env + identical op sequence ⇒ identical fault schedule,
which is what the determinism tests pin.

Env contract (``KFAC_FAULT_STORE_*``, registered in ``faults.py``'s
STRICT ``from_env`` so a typo'd drill fails loudly at build time):

  KFAC_FAULT_STORE_SEED     int; presence arms the chaos layer
  KFAC_FAULT_STORE_FAIL     P(an op raises StoreTimeout)         [0, 1]
  KFAC_FAULT_STORE_TORN     P(a put dies mid-upload: NOTHING is
                            committed — the torn-upload drill; the
                            atomicity contract says a reader must see
                            the old object or none, never a partial)
  KFAC_FAULT_STORE_PARTIAL  P(a get returns a PREFIX of the bytes —
                            the bit-rot/short-transfer drill the
                            manifest hash check must catch)
  KFAC_FAULT_STORE_STALE    P(a get returns the PREVIOUS blob this
                            process saw for the key)
  KFAC_FAULT_STORE_ACK_LOST P(a put COMMITS but its ack is lost — the
                            replay drill: the retry must land as the
                            original success via the idempotency
                            token, never as a self-conflict)
  KFAC_FAULT_STORE_WINDOWS  unavailability windows "10:40;90:95"
                            relative to T0 — every op inside a window
                            raises StoreTimeout (the 503-outage drill
                            the RetryPolicy must ride out or give up
                            on loudly)
  KFAC_FAULT_STORE_T0       wall-clock base of the windows (default:
                            config load time)

Faults apply at the WRAPPER, so both backends (and any future one) are
drillable identically; the retry layer sits OUTSIDE the chaos wrapper,
which is the point — retries are the system under test.
"""

import collections
import dataclasses
import hashlib
import os
import time
from typing import Tuple

from kfac_pytorch_tpu.store.base import (
    ANY, Blob, ObjectStore, StoreTimeout)

ENV_STORE_SEED = 'KFAC_FAULT_STORE_SEED'
ENV_STORE_FAIL = 'KFAC_FAULT_STORE_FAIL'
ENV_STORE_TORN = 'KFAC_FAULT_STORE_TORN'
ENV_STORE_PARTIAL = 'KFAC_FAULT_STORE_PARTIAL'
ENV_STORE_STALE = 'KFAC_FAULT_STORE_STALE'
ENV_STORE_ACK_LOST = 'KFAC_FAULT_STORE_ACK_LOST'
ENV_STORE_WINDOWS = 'KFAC_FAULT_STORE_WINDOWS'
ENV_STORE_T0 = 'KFAC_FAULT_STORE_T0'

STORE_ENVS = frozenset({
    ENV_STORE_SEED, ENV_STORE_FAIL, ENV_STORE_TORN, ENV_STORE_PARTIAL,
    ENV_STORE_STALE, ENV_STORE_ACK_LOST, ENV_STORE_WINDOWS,
    ENV_STORE_T0,
})


@dataclasses.dataclass(frozen=True)
class StoreFaultConfig:
    seed: int = 0
    fail: float = 0.0
    torn: float = 0.0
    partial: float = 0.0
    stale: float = 0.0
    ack_lost: float = 0.0
    windows: Tuple[Tuple[float, float], ...] = ()
    t0: float = 0.0

    @property
    def any_chaos(self):
        return bool(self.fail or self.torn or self.partial or self.stale
                    or self.ack_lost or self.windows)

    def unavailable(self, wall):
        rel = wall - self.t0
        return any(lo <= rel < hi for lo, hi in self.windows)


def _prob_env(env, e):
    raw = e.get(env)
    if not raw:
        return 0.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f'{env} must be a probability in [0, 1], '
                         f'got {raw!r}') from None
    if not 0.0 <= v <= 1.0:
        raise ValueError(f'{env} must be in [0, 1], got {v}')
    return v


def from_env(env=None):
    """Snapshot the store-fault environment, or None when no
    ``KFAC_FAULT_STORE_*`` variable is set. STRICT like
    ``faults.from_env`` (which delegates validation here)."""
    from kfac_pytorch_tpu.coord.chaos import parse_windows
    e = os.environ if env is None else env
    if not any(k in e for k in STORE_ENVS):
        return None
    raw_seed = e.get(ENV_STORE_SEED, '0')
    try:
        seed = int(raw_seed)
    except ValueError:
        raise ValueError(f'{ENV_STORE_SEED} must be an integer, '
                         f'got {raw_seed!r}') from None
    raw_t0 = e.get(ENV_STORE_T0)
    try:
        t0 = float(raw_t0) if raw_t0 else time.time()
    except ValueError:
        raise ValueError(f'{ENV_STORE_T0} must be a wall timestamp, '
                         f'got {raw_t0!r}') from None
    spec = e.get(ENV_STORE_WINDOWS)
    return StoreFaultConfig(
        seed=seed,
        fail=_prob_env(ENV_STORE_FAIL, e),
        torn=_prob_env(ENV_STORE_TORN, e),
        partial=_prob_env(ENV_STORE_PARTIAL, e),
        stale=_prob_env(ENV_STORE_STALE, e),
        ack_lost=_prob_env(ENV_STORE_ACK_LOST, e),
        windows=(parse_windows(spec, env=ENV_STORE_WINDOWS)
                 if spec else ()),
        t0=t0)


def _u(cfg, op, key, attempt, lane):
    """One uniform draw in [0, 1): a pure function of
    ``(seed, op, key, attempt)`` per fault lane — the determinism
    contract (SHA-256, stable across runs and interpreters)."""
    digest = hashlib.sha256(
        f'{cfg.seed}:{op}:{key}:{attempt}'.encode()).digest()
    i = lane * 8
    return int.from_bytes(digest[i:i + 8], 'big') / 2 ** 64


class ChaosStore(ObjectStore):
    """Wrap a store; inject the seeded fault schedule. ``trace``
    records every injected fault as ``(kind, op, key, attempt)`` —
    bounded, like the coordination chaos trace."""

    def __init__(self, inner, cfg, *, wall=time.time):
        self.inner = inner
        self.cfg = cfg
        self._wall = wall
        self._attempts = {}          # (op, key) -> count
        self._last_seen = {}         # key -> previous Blob (stale lane)
        self.trace = collections.deque(maxlen=65536)
        self.counts = collections.Counter()

    def __repr__(self):
        return f'ChaosStore({self.inner!r})'

    def _attempt(self, op, key):
        if len(self._attempts) > 65536:
            # bounded backstop (delete-op counters survive eviction):
            # keep the most recent half, insertion-ordered
            self._attempts = dict(
                list(self._attempts.items())[-32768:])
        k = (op, str(key))
        self._attempts[k] = n = self._attempts.get(k, 0) + 1
        return n

    def _inject(self, kind, op, key, attempt):
        self.counts[kind] += 1
        self.trace.append((kind, op, str(key), attempt))

    def _gate(self, op, key):
        """The fail/window lane shared by every op; returns the attempt
        index for the op-specific lanes."""
        attempt = self._attempt(op, key)
        if self.cfg.windows and self.cfg.unavailable(self._wall()):
            self._inject('window', op, key, attempt)
            raise StoreTimeout(
                f'injected store 503 window (op={op} key={key})')
        if self.cfg.fail and _u(self.cfg, op, key, attempt, 0) \
                < self.cfg.fail:
            self._inject('fail', op, key, attempt)
            raise StoreTimeout(
                f'injected store op failure (op={op} key={key} '
                f'attempt={attempt})')
        return attempt

    # -- reads -------------------------------------------------------------

    def get(self, key):
        attempt = self._gate('get', key)
        got = self.inner.get(key)
        if got is None:
            return None
        if self.cfg.partial and _u(self.cfg, 'get', key, attempt, 1) \
                < self.cfg.partial:
            # a short transfer: the bytes come back truncated but the
            # generation header is the committed one — exactly the
            # corruption shape only a content-hash check catches
            self._inject('partial', 'get', key, attempt)
            return Blob(got.data[:max(1, len(got.data) // 2)],
                        got.generation)
        prev = self._last_seen.get(key)
        if (prev is not None and prev.generation != got.generation
                and self.cfg.stale
                and _u(self.cfg, 'get', key, attempt, 2)
                < self.cfg.stale):
            self._inject('stale', 'get', key, attempt)
            return prev
        self._last_seen[key] = got
        return got

    def head(self, key):
        self._gate('head', key)
        return self.inner.head(key)

    def list(self, prefix=''):
        self._gate('list', prefix)
        return self.inner.list(prefix)

    def list_meta(self, prefix=''):
        self._gate('list_meta', prefix)
        return self.inner.list_meta(prefix)

    # -- writes ------------------------------------------------------------

    def put(self, key, data, *, if_generation=ANY, token=None):
        attempt = self._gate('put', key)
        if self.cfg.torn and _u(self.cfg, 'put', key, attempt, 1) \
                < self.cfg.torn:
            # the upload died mid-stream; the server discarded the
            # partial (the atomicity contract) — nothing committed,
            # the writer sees a transient failure and retries
            self._inject('torn', 'put', key, attempt)
            raise StoreTimeout(
                f'injected torn upload (op=put key={key} '
                f'attempt={attempt})')
        gen = self.inner.put(key, data, if_generation=if_generation,
                             token=token)
        if self.cfg.ack_lost and _u(self.cfg, 'put', key, attempt, 3) \
                < self.cfg.ack_lost:
            # the object COMMITTED but the ack was lost on the wire —
            # the retry above must replay the same idempotency token
            # and land as the original success
            self._inject('ack_lost', 'put', key, attempt)
            raise StoreTimeout(
                f'injected lost put ack (op=put key={key} '
                f'attempt={attempt})')
        return gen

    def delete(self, key):
        self._gate('delete', key)
        self._evict(key)
        return self.inner.delete(key)

    def delete_prefix(self, prefix):
        self._gate('delete_prefix', prefix)
        for key in [k for k in self._last_seen
                    if k.startswith(str(prefix))]:
            self._evict(key)
        for key in {k for _op, k in self._attempts
                    if k.startswith(str(prefix))}:
            self._evict(key)
        return self.inner.delete_prefix(prefix)

    def _evict(self, key):
        """Deleted keys drop their fault-lane state: checkpoint keys
        are pruned over a long run and these maps must not grow
        monotonically. The delete ops' own counters are KEPT —
        resetting them mid-retry would redraw attempt 1 forever and
        turn one injected delete failure into a permanent one."""
        key = str(key)
        self._last_seen.pop(key, None)
        for pair in [p for p in self._attempts
                     if p[1] == key
                     and p[0] not in ('delete', 'delete_prefix')]:
            del self._attempts[pair]

    def close(self):
        self.inner.close()


def maybe_wrap(store, cfg=None):
    """Wrap ``store`` in a :class:`ChaosStore` when the chaos env is
    armed (or an explicit ``cfg`` is given); otherwise return it
    untouched — the one-liner every store construction site uses."""
    if cfg is None:
        cfg = from_env()
    if cfg is None or not cfg.any_chaos:
        return store
    return ChaosStore(store, cfg)
