"""A GCS-style HTTP object store: stdlib single-process server
(``kfac-store-serve``) + client backend — no shared filesystem
anywhere in the durability plane.

Protocol (deliberately a miniature of the GCS JSON/XML API shape —
whole-object semantics, generation preconditions, list-by-prefix):

  ``PUT /o/<key>``       body = object bytes; commit is atomic under
                         the server lock. Preconditions ride headers:
                         ``X-Kfac-If-Generation: <gen>`` (replace that
                         exact version), ``X-Kfac-If-Generation:
                         absent`` (create only), no header =
                         unconditional. ``X-Kfac-Token`` is the
                         idempotency token: a REPLAY of the last
                         applied token for a key answers 200 with the
                         original generation — an ack lost on the wire
                         must not turn the retry into a self-conflict.
                         412 = precondition failed (an ANSWER).
  ``GET /o/<key>``       200 body + ``X-Kfac-Generation``; 404 missing.
  ``HEAD /o/<key>``      as GET, no body, plus ``X-Kfac-Size``.
  ``DELETE /o/<key>``    200 ``{"deleted": true|false}``.
  ``GET /list?prefix=``  200 ``{"keys": {key: {"generation": g,
                         "size": n}}}`` — ONE round trip for the whole
                         scrub scan.
  ``POST /delete-prefix?prefix=``  200 ``{"deleted": n}``.

Generations are the same content hashes the posix backend mints
(sha256 of the bytes, truncated), so an object has ONE token no matter
which backend holds it — ``kfac-ckpt-verify`` repairs across backends
by token equality.

Objects live in server memory: the server is the durability *boundary*
for the processes it serves (a SIGKILLed trainer's committed objects
survive in it), exactly the role the in-process KV server plays for
the coordination plane. Client-side transient failures (connection
refused, torn response) raise :class:`~.base.StoreTimeout`; the retry
wrapper above decides how hard to try.
"""

import argparse
import http.client
import http.server
import json
import logging
import signal
import threading
import urllib.parse

from kfac_pytorch_tpu.store.base import (
    ANY, Blob, Meta, ObjectStore, StoreTimeout, check_key, check_prefix)
from kfac_pytorch_tpu.store.posix import generation_of

log = logging.getLogger(__name__)

DEFAULT_STORE_PORT = 8490


class StoreHttpServer:
    """Single-process in-memory object store behind a threading HTTP
    server. ``start()`` binds (port 0 picks a free port), ``stop()``
    shuts down; state is one dict under one lock — whole-object
    commits are atomic by construction, a reader can NEVER observe a
    partial object."""

    def __init__(self, host='127.0.0.1', port=DEFAULT_STORE_PORT):
        self.host = host
        self.port = int(port)
        self._objects = {}    # key -> (bytes, generation)
        self._tokens = {}     # key -> (token, generation) last applied
        self._lock = threading.Lock()
        self._httpd = None
        self._thread = None

    # -- object ops (server side, under the lock) --------------------------

    def _op_put(self, key, data, if_generation, token):
        with self._lock:
            if token is not None:
                last = self._tokens.get(key)
                if last is not None and last[0] == token:
                    # idempotent replay: the previous attempt committed
                    # and only its ack was lost — answer the original
                    # success, do NOT re-evaluate the precondition
                    # against our own write
                    return last[1]
            cur = self._objects.get(key)
            if if_generation == 'absent':
                if cur is not None:
                    return None
            elif if_generation is not None:
                if cur is None or cur[1] != if_generation:
                    return None
            gen = generation_of(data)
            self._objects[key] = (bytes(data), gen)
            if token is not None:
                self._tokens[key] = (token, gen)
            return gen

    def _op_get(self, key):
        with self._lock:
            return self._objects.get(key)

    def _op_delete(self, key):
        with self._lock:
            self._tokens.pop(key, None)
            return self._objects.pop(key, None) is not None

    def _op_list(self, prefix):
        with self._lock:
            return {k: {'generation': g, 'size': len(d)}
                    for k, (d, g) in sorted(self._objects.items())
                    if k.startswith(prefix)}

    def _op_delete_prefix(self, prefix):
        with self._lock:
            hit = [k for k in self._objects if k.startswith(prefix)]
            for k in hit:
                self._objects.pop(k, None)
                self._tokens.pop(k, None)
            return len(hit)

    # -- http plumbing -----------------------------------------------------

    def start(self):
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, fmt, *args):  # route through logging
                log.debug('store-serve: ' + fmt, *args)

            def _reply(self, status, payload=None, headers=(),
                       body=None):
                raw = body
                if raw is None:
                    raw = (json.dumps(payload).encode()
                           if payload is not None else b'')
                self.send_response(status)
                self.send_header('Content-Length', str(len(raw)))
                for name, value in headers:
                    self.send_header(name, value)
                self.end_headers()
                if self.command != 'HEAD':
                    self.wfile.write(raw)

            def _key(self):
                path = urllib.parse.urlparse(self.path).path
                if not path.startswith('/o/'):
                    return None
                return urllib.parse.unquote(path[len('/o/'):])

            def _query(self, name):
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                return q.get(name, [''])[0]

            def do_GET(self):
                path = urllib.parse.urlparse(self.path).path
                if path == '/list':
                    self._reply(200, {'keys': server._op_list(
                        self._query('prefix'))})
                    return
                key = self._key()
                if key is None:
                    self._reply(404, {'error': 'bad path'})
                    return
                got = server._op_get(key)
                if got is None:
                    self._reply(404, {'error': 'not found'})
                    return
                data, gen = got
                self._reply(200, headers=(
                    ('X-Kfac-Generation', gen),
                    ('X-Kfac-Size', str(len(data)))), body=data)

            def do_HEAD(self):
                key = self._key()
                got = server._op_get(key) if key else None
                if got is None:
                    self._reply(404)
                    return
                data, gen = got
                self._reply(200, headers=(
                    ('X-Kfac-Generation', gen),
                    ('X-Kfac-Size', str(len(data)))), body=b'')

            def do_PUT(self):
                key = self._key()
                if key is None:
                    self._reply(404, {'error': 'bad path'})
                    return
                length = int(self.headers.get('Content-Length') or 0)
                data = self.rfile.read(length)
                if len(data) != length:
                    # the upload died mid-stream: discard the partial —
                    # a torn upload must never become a visible object
                    self._reply(400, {'error': 'torn upload discarded'})
                    return
                gen = server._op_put(
                    key, data,
                    self.headers.get('X-Kfac-If-Generation'),
                    self.headers.get('X-Kfac-Token'))
                if gen is None:
                    self._reply(412, {'error': 'precondition failed'})
                    return
                self._reply(200, {'generation': gen})

            def do_DELETE(self):
                key = self._key()
                if key is None:
                    self._reply(404, {'error': 'bad path'})
                    return
                self._reply(200, {'deleted': server._op_delete(key)})

            def do_POST(self):
                path = urllib.parse.urlparse(self.path).path
                if path == '/delete-prefix':
                    prefix = self._query('prefix')
                    if not prefix:
                        self._reply(400, {'error': 'empty prefix'})
                        return
                    self._reply(200, {
                        'deleted': server._op_delete_prefix(prefix)})
                    return
                self._reply(404, {'error': 'bad path'})

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name='kfac-store-serve',
            daemon=True)
        self._thread.start()
        return self

    @property
    def address(self):
        return f'{self.host}:{self.port}'

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class HttpStore(ObjectStore):
    """Client for :class:`StoreHttpServer`. ``namespace`` prefixes
    every key (the per-tenant checkpoint dir path), so disjoint
    directories stay disjoint stores on one server — the same
    namespacing contract the KV backend uses."""

    def __init__(self, addr, namespace='', timeout=5.0):
        host, _, port = str(addr).rpartition(':')
        if not host or not port.isdigit():
            raise ValueError(
                f'store address must be "host:port", got {addr!r}')
        self.host, self.port = host, int(port)
        self.namespace = str(namespace).strip('/')
        self.timeout = float(timeout)
        self._local = threading.local()

    def __repr__(self):
        return (f'HttpStore({self.host}:{self.port}, '
                f'namespace={self.namespace!r})')

    def _full(self, key):
        key = check_key(key)
        return f'{self.namespace}/{key}' if self.namespace else key

    def _full_prefix(self, prefix):
        prefix = check_prefix(prefix)
        if not self.namespace:
            return prefix
        return f'{self.namespace}/{prefix}' if prefix \
            else f'{self.namespace}/'

    def _strip(self, key):
        if self.namespace and key.startswith(self.namespace + '/'):
            return key[len(self.namespace) + 1:]
        return key

    def _request(self, method, path, body=None, headers=()):
        conn = getattr(self._local, 'conn', None)
        for fresh in (False, True):
            if conn is None or fresh:
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout)
                self._local.conn = conn
            try:
                conn.request(method, path, body=body,
                             headers=dict(headers))
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                self._local.conn = None
                conn = None
                if fresh:
                    raise StoreTimeout(
                        f'store server {self.host}:{self.port} '
                        f'unreachable: {e}') from e
                # one silent reconnect: the server may have closed an
                # idle keep-alive connection between ops
        raise AssertionError('unreachable')

    def _obj_path(self, full_key):
        return '/o/' + urllib.parse.quote(full_key)

    # -- ops ---------------------------------------------------------------

    def get(self, key):
        status, headers, data = self._request(
            'GET', self._obj_path(self._full(key)))
        if status == 404:
            return None
        if status != 200:
            raise StoreTimeout(f'store get {key!r}: HTTP {status}')
        return Blob(data, headers.get('X-Kfac-Generation', ''))

    def head(self, key):
        status, headers, _ = self._request(
            'HEAD', self._obj_path(self._full(key)))
        if status == 404:
            return None
        if status != 200:
            raise StoreTimeout(f'store head {key!r}: HTTP {status}')
        return Meta(headers.get('X-Kfac-Generation', ''),
                    int(headers.get('X-Kfac-Size') or 0))

    def put(self, key, data, *, if_generation=ANY, token=None):
        headers = []
        if if_generation is None:
            headers.append(('X-Kfac-If-Generation', 'absent'))
        elif if_generation is not ANY:
            headers.append(('X-Kfac-If-Generation', str(if_generation)))
        if token is not None:
            headers.append(('X-Kfac-Token', str(token)))
        status, _, body = self._request(
            'PUT', self._obj_path(self._full(key)), body=bytes(data),
            headers=headers)
        if status == 412:
            return None  # precondition answer, never an error
        if status != 200:
            raise StoreTimeout(f'store put {key!r}: HTTP {status}')
        try:
            return json.loads(body.decode())['generation']
        except (ValueError, KeyError) as e:
            raise StoreTimeout(
                f'store put {key!r}: torn response') from e

    def delete(self, key):
        status, _, body = self._request(
            'DELETE', self._obj_path(self._full(key)))
        if status != 200:
            raise StoreTimeout(f'store delete {key!r}: HTTP {status}')
        try:
            return bool(json.loads(body.decode())['deleted'])
        except (ValueError, KeyError) as e:
            raise StoreTimeout(
                f'store delete {key!r}: torn response') from e

    def _list_meta_raw(self, prefix):
        full = self._full_prefix(prefix)
        status, _, body = self._request(
            'GET', '/list?prefix=' + urllib.parse.quote(full, safe=''))
        if status != 200:
            raise StoreTimeout(f'store list {prefix!r}: HTTP {status}')
        try:
            keys = json.loads(body.decode())['keys']
        except (ValueError, KeyError) as e:
            raise StoreTimeout(
                f'store list {prefix!r}: torn response') from e
        return {self._strip(k): v for k, v in keys.items()}

    def list(self, prefix=''):
        return sorted(self._list_meta_raw(prefix))

    def list_meta(self, prefix=''):
        # ONE round trip for the whole scan — the scrub contract
        return {k: Meta(v.get('generation', ''), v.get('size', 0))
                for k, v in self._list_meta_raw(prefix).items()}

    def delete_prefix(self, prefix):
        prefix = check_prefix(prefix)
        if not prefix:
            raise ValueError('delete_prefix needs a non-empty prefix '
                             '(refusing to wipe the whole namespace)')
        full = self._full_prefix(prefix)
        status, _, body = self._request(
            'POST',
            '/delete-prefix?prefix=' + urllib.parse.quote(full, safe=''))
        if status != 200:
            raise StoreTimeout(
                f'store delete_prefix {prefix!r}: HTTP {status}')
        try:
            return int(json.loads(body.decode())['deleted'])
        except (ValueError, KeyError) as e:
            raise StoreTimeout(
                f'store delete_prefix {prefix!r}: torn response') from e

    def close(self):
        conn = getattr(self._local, 'conn', None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def main(argv=None):
    """``kfac-store-serve``: run the object-store server in the
    foreground until SIGTERM/SIGINT."""
    parser = argparse.ArgumentParser(
        prog='kfac-store-serve',
        description='single-process GCS-style object store for the '
                    'kfac checkpoint plane')
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=DEFAULT_STORE_PORT,
                        help='listen port (0 picks a free one)')
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(name)s %(levelname)s %(message)s')
    server = StoreHttpServer(args.host, args.port).start()
    print(f'kfac-store-serve: listening on {server.address}',
          flush=True)
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    try:
        done.wait()
    finally:
        server.stop()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
