"""``kfac-ckpt-verify``: scrub a checkpoint namespace against its
manifests, repair what can be repaired, report the rest.

The scrubber walks every committed epoch (every manifest) in a
namespace, re-hashes every blob, and classifies each mismatch as
``missing`` / ``size_mismatch`` / ``hash_mismatch``. A corrupt blob is
repaired from, in order:

1. a **mirror** namespace (``--mirror DIR``): a second copy of the
   same keys — the replica-repair path; a candidate is accepted only
   if its bytes hash to the manifest's recorded sha256, so a corrupt
   mirror can never "repair" corruption into place;
2. an **older committed epoch** holding a blob with the SAME recorded
   hash — identical content under a different key (hash equality is
   the match, so this can never substitute different state).

``--sync-mirror`` additionally copies every blob that verifies clean
(and the manifest itself) INTO the mirror — the scrub doubles as the
backup pass that makes the next scrub's repairs possible.

Every event is one greppable log line in the incident grammar
(``ckpt: verified/corrupt/repaired ...``), so the ``kfac-obs``
timeline renders a scrub with zero new aggregation code. Exit code:
0 when every epoch verifies (possibly after repair), 1 when
unrepaired corruption remains, ``RC_STORE_LOST`` (120) when the store
itself is gone.

jax-free by design: the scrubber runs on any host that can reach the
store, training environment or not.
"""

import argparse
import logging
import sys

from kfac_pytorch_tpu.store import (
    RC_STORE_LOST, PosixStore, RetryingStore, StoreGiveUp,
    store_from_env)
from kfac_pytorch_tpu.store.manifest import (
    blob_sha256, manifest_epochs, manifest_key, parse_manifest,
    verify_blob)

log = logging.getLogger(__name__)


def _repair_from_mirror(store, mirror, key, spec):
    if mirror is None:
        return False
    blob = mirror.get(key)
    if blob is None or blob_sha256(blob.data) != spec['sha256'] \
            or len(blob.data) != spec['size']:
        return False
    store.put(key, blob.data)
    return True


def _repair_from_epoch(store, manifests, epoch, spec):
    """Find an OLDER committed epoch holding a blob whose recorded
    hash equals ``spec``'s, read it, and return its bytes if they
    still verify — content-addressed repair, never state substitution."""
    for other in sorted((e for e in manifests if e < epoch),
                        reverse=True):
        manifest = manifests[other]
        for other_key, other_spec in sorted(manifest['blobs'].items()):
            if other_spec['sha256'] != spec['sha256'] \
                    or other_spec['size'] != spec['size']:
                continue
            blob = store.get(other_key)
            if blob is not None \
                    and blob_sha256(blob.data) == spec['sha256']:
                return other, blob.data
    return None, None


def scrub(store, *, mirror=None, repair=True, sync_mirror=False):
    """Verify every committed epoch in ``store``; returns
    ``(verified_epochs, repaired, unrepaired)`` counts. ``mirror`` is
    a plain :class:`ObjectStore` (or None)."""
    epochs = manifest_epochs(store)
    manifests = {}
    for epoch in sorted(epochs):
        blob = store.get(epochs[epoch])
        manifest = parse_manifest(blob.data) if blob is not None \
            else None
        if manifest is None:
            log.warning(
                'ckpt: corrupt blob key=%s epoch=%d reason=%s',
                epochs[epoch], epoch, 'bad_manifest')
            continue
        manifests[epoch] = manifest
    verified = repaired = unrepaired = 0
    for epoch in sorted(manifests):
        manifest = manifests[epoch]
        bad = 0
        for key in sorted(manifest['blobs']):
            spec = manifest['blobs'][key]
            reason = verify_blob(store, key, spec)
            if reason is None:
                continue
            log.warning('ckpt: corrupt blob key=%s epoch=%d reason=%s',
                        key, epoch, reason)
            if repair:
                if _repair_from_mirror(store, mirror, key, spec):
                    source = 'mirror'
                else:
                    other, data = _repair_from_epoch(
                        store, manifests, epoch, spec)
                    source = None
                    if data is not None:
                        store.put(key, data)
                        source = f'epoch-{other}'
                if source is not None \
                        and verify_blob(store, key, spec) is None:
                    log.warning(
                        'ckpt: repaired blob key=%s epoch=%d source=%s '
                        '[resilience: ckpt_repaired=1]',
                        key, epoch, source)
                    repaired += 1
                    continue
            bad += 1
            unrepaired += 1
        if bad == 0:
            verified += 1
            log.info('ckpt: verified epoch=%d blobs=%d',
                     epoch, len(manifest['blobs']))
            if sync_mirror and mirror is not None:
                for key in sorted(manifest['blobs']):
                    blob = store.get(key)
                    if blob is not None:
                        mirror.put(key, blob.data)
                mblob = store.get(manifest_key(epoch))
                if mblob is not None:
                    mirror.put(manifest_key(epoch), mblob.data)
        else:
            log.error(
                'ckpt: epoch %d has %d unrepaired corrupt blob(s) — '
                'auto_resume will skip it', epoch, bad)
    return verified, repaired, unrepaired


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='kfac-ckpt-verify',
        description='scrub a checkpoint namespace against its '
                    'manifests; repair corrupt blobs from a mirror or '
                    'an older epoch')
    parser.add_argument('--root', required=True,
                        help='checkpoint namespace (the run/tenant '
                             'ckpt dir; backend selection rides '
                             'KFAC_STORE_BACKEND / KFAC_STORE_ADDR)')
    parser.add_argument('--mirror', default=None, metavar='DIR',
                        help='posix mirror namespace used as a repair '
                             'source')
    parser.add_argument('--sync-mirror', action='store_true',
                        help='copy verified blobs + manifests into '
                             '--mirror (the backup pass)')
    parser.add_argument('--no-repair', action='store_true',
                        help='report only; never write to the store')
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(name)s %(levelname)s %(message)s')
    store = store_from_env(args.root)
    mirror = None
    if args.mirror:
        # the repair source must stay truthful: retry for liveness,
        # but never chaos-wrap the mirror a drill repairs from
        mirror = RetryingStore(PosixStore(args.mirror))
    try:
        verified, repaired, unrepaired = scrub(
            store, mirror=mirror, repair=not args.no_repair,
            sync_mirror=args.sync_mirror)
    except StoreGiveUp as e:
        log.error(
            'checkpoint store lost — %s; exiting rc=%d '
            '[resilience: store_lost=1]', e, RC_STORE_LOST)
        return RC_STORE_LOST
    log.info('ckpt-verify: %d epoch(s) verified, %d blob(s) repaired, '
             '%d unrepaired', verified, repaired, unrepaired)
    return 1 if unrepaired else 0


if __name__ == '__main__':
    sys.exit(main())
