"""Pluggable object-store backends for the durable checkpoint plane.

The checkpoint system used to bottom out on direct filesystem writes
into the run directory — atomic locally, but with no integrity story:
a torn write or a bit-rotted blob was only discovered when
``auto_resume`` crashed into it. This package names the primitives the
checkpoint plane actually needs (:class:`~.base.ObjectStore`: whole-
object get / head / preconditioned put with generation tokens /
delete / prefix list) and ships two implementations:

- :class:`~.posix.PosixStore` — the default; byte-compatible with the
  existing checkpoint files, so every drill, test and operator
  ``ls`` works unchanged.
- :class:`~.httpstore.HttpStore` — a single-process GCS-style HTTP
  object server (``kfac-store-serve``) with content-hash generations,
  preconditioned puts and idempotent ack-lost replay; no shared
  filesystem anywhere in the durability plane.

Plus the two wrappers that make the plane *testable* and *survivable*:
:class:`~.chaos.ChaosStore` (seeded ``KFAC_FAULT_STORE_*`` fault
injection — torn uploads, partial/stale reads, 503 windows, lost put
acks) and :class:`~.base.RetryingStore` (bounded per-op backoff +
jitter with a loud give-up). Selection is one env pair::

    KFAC_STORE_BACKEND=posix          # default: the run directory
    KFAC_STORE_BACKEND=http KFAC_STORE_ADDR=host:8490

:func:`store_from_env` builds the full stack (base store → chaos
wrapper when armed → retry wrapper) for a given *root* (the checkpoint
base dir — on the HTTP server it becomes the key namespace, so
disjoint per-tenant checkpoint dirs stay disjoint stores).

On top sits the manifest plane (:mod:`.manifest`): every committed
epoch is named by a content-hash manifest written LAST, and
``kfac-ckpt-verify`` (:mod:`.verify`) scrubs and repairs namespaces
offline.
"""

import os

from kfac_pytorch_tpu.store.base import (
    ANY, Blob, Meta, ObjectStore, RetryingStore, StoreError,
    StoreGiveUp, StoreTimeout, default_retry_policy)
from kfac_pytorch_tpu.store.chaos import (
    STORE_ENVS, ChaosStore, StoreFaultConfig)
from kfac_pytorch_tpu.store.chaos import from_env as chaos_from_env
from kfac_pytorch_tpu.store.chaos import maybe_wrap as maybe_wrap_chaos
from kfac_pytorch_tpu.store.httpstore import (
    DEFAULT_STORE_PORT, HttpStore, StoreHttpServer)
from kfac_pytorch_tpu.store.posix import PosixStore, generation_of

#: backend selection env contract (exported by launchers / the service
#: scheduler to every supervisor and trainer of a run)
ENV_BACKEND = 'KFAC_STORE_BACKEND'
ENV_ADDR = 'KFAC_STORE_ADDR'

#: "the durability plane is gone": exit code of a trainer or verifier
#: whose store ops exhausted their retry budget (:class:`StoreGiveUp`).
#: Distinct from the trainer-protocol codes (113/114/115), the
#: membership verdicts (116/117/119) and ``RC_COORD_LOST`` (118): the
#: operator's reaction is to check the OBJECT STORE (is the
#: kfac-store-serve server up at ``KFAC_STORE_ADDR``? is the checkpoint
#: filesystem mounted?), not the pod and not the coordination backend —
#: a host that cannot commit checkpoints must stop loudly rather than
#: train on with nothing durable behind it.
RC_STORE_LOST = 120


def store_from_env(root, *, retry=True, policy=None, chaos=True,
                   env=None, clock=None, rng=None):
    """Build the object-store stack for ``root``.

    ``root`` is the checkpoint namespace — the run's checkpoint base
    dir, or a tenant's ``ckpt`` dir under the service. ``posix``
    (default) maps it onto that directory; ``http`` namespaces keys
    under it on the server at ``KFAC_STORE_ADDR``. ``retry=False``
    skips the retry wrapper; ``chaos=False`` skips fault injection
    (reserved for consumers that must stay truthful, e.g. the repair
    writer inside ``kfac-ckpt-verify``).
    """
    e = os.environ if env is None else env
    kind = (e.get(ENV_BACKEND) or 'posix').strip().lower()
    if kind in ('posix', 'file', ''):
        store = PosixStore(root)
    elif kind == 'http':
        addr = (e.get(ENV_ADDR) or '').strip()
        if not addr:
            raise ValueError(
                f'{ENV_BACKEND}=http needs {ENV_ADDR} ("host:port" of '
                'a kfac-store-serve object server)')
        store = HttpStore(addr, namespace=str(root))
    else:
        raise ValueError(f'{ENV_BACKEND} must be "posix" or "http", '
                         f'got {kind!r}')
    if chaos:
        store = maybe_wrap_chaos(store, chaos_from_env(env=e))
    if retry:
        store = RetryingStore(store, policy=policy, clock=clock,
                              rng=rng)
    return store


#: short alias, mirroring ``coord.from_env`` / ``faults.from_env``
from_env = store_from_env


def local_root(store):
    """The local directory a store stack bottoms out on, or ``None``
    for a remote backend — the checkpoint plane uses this to skip
    re-uploading files a local writer (orbax) already placed exactly
    where the posix store would put them."""
    inner = store
    while True:
        if isinstance(inner, PosixStore):
            return os.path.abspath(inner.root)
        nxt = getattr(inner, 'inner', None)
        if nxt is None:
            return None
        inner = nxt


__all__ = [
    'ANY', 'Blob', 'Meta', 'ObjectStore', 'StoreError', 'StoreGiveUp',
    'StoreTimeout', 'RetryingStore', 'default_retry_policy',
    'PosixStore', 'HttpStore', 'StoreHttpServer', 'DEFAULT_STORE_PORT',
    'ChaosStore', 'StoreFaultConfig', 'STORE_ENVS', 'chaos_from_env',
    'maybe_wrap_chaos', 'generation_of', 'ENV_BACKEND', 'ENV_ADDR',
    'RC_STORE_LOST', 'store_from_env', 'from_env', 'local_root',
]
