"""The object-store contract: the durability plane under the
checkpoint system, named the way ``coord.base`` names the
coordination plane.

Checkpoints, manifests and run artifacts are *objects*: opaque byte
blobs under hierarchical keys, written whole, read whole, and — the
property everything above relies on — **never observable half-written**.
:class:`ObjectStore` names the five primitives the checkpoint plane
actually uses:

- ``get(key) -> Blob | None`` — the full bytes plus the generation
  token they were committed under; missing is ``None`` (transient
  backend failures raise, they are not "missing").
- ``head(key) -> Meta | None`` — generation + size without the bytes
  (the scrub scan's primitive: a verifier sizing a namespace must not
  download it).
- ``put(key, data, if_generation=...) -> generation | None`` —
  atomic whole-object commit with an optional precondition:
  :data:`ANY` skips the check (unconditional), ``None`` means *create
  only if absent*, a generation token means *replace exactly that
  version*. ``None`` return is a precondition ANSWER (someone else
  moved the object), never an error.
- ``delete(key)`` / ``delete_prefix(prefix)`` — idempotent removal.
- ``list(prefix)`` / ``list_meta(prefix)`` — prefix scans.

Generations are content hashes (sha256 of the object bytes,
truncated) on every backend, so the SAME object has the SAME
generation on the posix store and on the HTTP store — the contract
tests pin that, and it is what lets ``kfac-ckpt-verify`` repair a blob
from a mirror by token equality alone.

Error model: every transient failure raises :class:`StoreTimeout` (an
:class:`OSError` subclass — the callers' existing flaky-filesystem
handling applies verbatim); :class:`RetryingStore` adds the bounded
per-op retry with the loud ``[resilience: store_gave_up=1]`` give-up
that escalates to :data:`~kfac_pytorch_tpu.store.RC_STORE_LOST`.

Zero dependencies, jax-free (``kfac-ckpt-verify`` runs without a
training environment).
"""

import logging
import threading

log = logging.getLogger(__name__)


def _res():
    # lazy: the resilience package may import store consumers — a
    # module-level import back into it would make import order matter
    from kfac_pytorch_tpu import resilience
    return resilience


class StoreError(OSError):
    """Base class for object-store failures. An ``OSError`` on
    purpose: checkpoint writers already treat storage failures as
    OSErrors (retry policies, scan-downward resume)."""


class StoreTimeout(StoreError):
    """A transient backend failure (unreachable server, 503 window,
    upload died mid-stream). Retryable."""


class StoreGiveUp(StoreError):
    """The retry budget for one operation is spent. Raised by
    :class:`RetryingStore` after logging the loud give-up form; the
    checkpoint plane exits :data:`~kfac_pytorch_tpu.store.RC_STORE_LOST`
    on it instead of wedging against a dead durability plane."""


class _Any:
    def __repr__(self):
        return '<store.ANY>'


#: ``put`` precondition sentinel: skip the generation check
#: (unconditional write — distinct from ``if_generation=None``, which
#: means "create only if the object does not exist yet").
ANY = _Any()


class Blob:
    """A read result: the object bytes plus the generation token they
    were committed under (feed it back to ``put(if_generation=...)``)."""

    __slots__ = ('data', 'generation')

    def __init__(self, data, generation):
        self.data = data
        self.generation = generation

    def __iter__(self):  # tuple-unpack convenience: data, gen = blob
        yield self.data
        yield self.generation

    def __repr__(self):
        return (f'Blob({len(self.data)} bytes, '
                f'generation={self.generation!r})')


class Meta:
    """A ``head`` result: generation + size, no bytes."""

    __slots__ = ('generation', 'size')

    def __init__(self, generation, size):
        self.generation = generation
        self.size = int(size)

    def __repr__(self):
        return f'Meta(generation={self.generation!r}, size={self.size})'


def check_key(key):
    """Keys are relative ``/``-joined paths; reject escapes so a POSIX
    backend can never be walked out of its root."""
    key = str(key)
    if not key or key.startswith('/') or '\\' in key:
        raise ValueError(f'bad store key {key!r}')
    if any(part in ('', '.', '..') for part in key.split('/')):
        raise ValueError(f'bad store key {key!r}')
    return key


def check_prefix(prefix):
    """Prefixes share the key grammar ('' = everything, one trailing
    ``/`` allowed) — and the same escape rejection."""
    prefix = str(prefix)
    if not prefix:
        return prefix
    if prefix.startswith('/') or '\\' in prefix:
        raise ValueError(f'bad store prefix {prefix!r}')
    parts = prefix.split('/')
    if parts and parts[-1] == '':
        parts = parts[:-1]
    if any(part in ('', '.', '..') for part in parts):
        raise ValueError(f'bad store prefix {prefix!r}')
    return prefix


class ObjectStore:
    """Interface + shared conveniences. Subclasses implement ``get``,
    ``head``, ``put``, ``delete``, ``delete_prefix`` and ``list``."""

    # -- required primitives ----------------------------------------------

    def get(self, key):
        raise NotImplementedError

    def head(self, key):
        raise NotImplementedError

    def put(self, key, data, *, if_generation=ANY, token=None):
        """``token``: optional idempotency token for replay-safe puts
        over a lossy wire — a backend that can remember the last
        applied writer (the HTTP server) answers a REPLAY of the same
        token with the original success instead of a precondition
        conflict against its own write. Local backends may ignore it
        (their commit cannot lose an ack)."""
        raise NotImplementedError

    def delete(self, key):
        raise NotImplementedError

    def delete_prefix(self, prefix):
        raise NotImplementedError

    def list(self, prefix=''):
        raise NotImplementedError

    # -- derived ----------------------------------------------------------

    def list_meta(self, prefix=''):
        """{key: Meta} for every object under ``prefix`` — the scrub
        scan. Derived default is list + head per key; backends with a
        server-side scan override it with ONE round trip."""
        out = {}
        for key in self.list(prefix):
            meta = self.head(key)
            if meta is not None:
                out[key] = meta
        return out

    def close(self):
        pass


def default_retry_policy():
    """Default per-op policy: small, bounded, jittered — a store op
    sits inside the checkpoint critical path (and the preemption grace
    window), so the whole budget must stay in the seconds range (give
    up loudly rather than stall a grace-window save past its
    deadline)."""
    from kfac_pytorch_tpu.resilience.retry import RetryPolicy
    return RetryPolicy(attempts=5, base_delay=0.1, max_delay=2.0,
                       multiplier=2.0, jitter=0.5,
                       retry_on=(StoreTimeout,))


class RetryingStore(ObjectStore):
    """Per-op bounded retry (backoff + jitter) around any store.

    Every retry bumps the process-global ``store_retries`` counter;
    exhausting the budget logs the machine-greppable give-up form and
    raises :class:`StoreGiveUp` so the caller can exit
    :data:`~kfac_pytorch_tpu.store.RC_STORE_LOST` instead of wedging.
    Precondition conflicts are answers, not failures — they never
    retry.
    """

    def __init__(self, inner, *, policy=None, clock=None, rng=None,
                 log=None):
        import random

        from kfac_pytorch_tpu.resilience.retry import REAL_CLOCK
        self.inner = inner
        self.policy = policy or default_retry_policy()
        self.clock = clock or REAL_CLOCK
        self.rng = rng or random
        self.log = log if log is not None else logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._retries = 0
        self._gave_up = 0
        self._wait_s = 0.0

    def stats(self):
        with self._lock:
            return {'retries': self._retries, 'gave_up': self._gave_up,
                    'wait_s': self._wait_s}

    def _call(self, op, key, fn):
        last = None
        for attempt in range(self.policy.attempts):
            try:
                return fn()
            except self.policy.retry_on as e:
                last = e
                if attempt == self.policy.attempts - 1:
                    break
                delay = self.policy.delay(attempt, self.rng)
                with self._lock:
                    self._retries += 1
                    self._wait_s += delay
                _res().counters.bump('store_retries')
                self.log.warning(
                    'store: retry %d/%d op=%s key=%s in %.2fs after: %s',
                    attempt + 1, self.policy.attempts - 1, op, key,
                    delay, e)
                self.clock.sleep(delay)
        with self._lock:
            self._gave_up += 1
        _res().counters.bump('store_gave_ups')
        self.log.error(
            'store: giving up op=%s key=%s after %d attempts (%s) '
            '[resilience: store_gave_up=1]', op, key,
            self.policy.attempts, last)
        raise StoreGiveUp(
            f'object store op {op} on {key!r} failed '
            f'{self.policy.attempts} times: {last}') from last

    # -- delegated ops ----------------------------------------------------

    def get(self, key):
        return self._call('get', key, lambda: self.inner.get(key))

    def head(self, key):
        return self._call('head', key, lambda: self.inner.head(key))

    def put(self, key, data, *, if_generation=ANY, token=None):
        # ONE idempotency token per logical put, shared by every retry
        # attempt: an ack lost after the server committed the object
        # must read as success on the replay, never as a precondition
        # self-conflict that makes the caller believe someone else
        # moved the object
        if token is None:
            import os as _os
            token = _os.urandom(8).hex()
        return self._call('put', key, lambda: self.inner.put(
            key, data, if_generation=if_generation, token=token))

    def delete(self, key):
        return self._call('delete', key, lambda: self.inner.delete(key))

    def delete_prefix(self, prefix):
        return self._call('delete_prefix', prefix,
                          lambda: self.inner.delete_prefix(prefix))

    def list(self, prefix=''):
        return self._call('list', prefix, lambda: self.inner.list(prefix))

    def list_meta(self, prefix=''):
        return self._call('list_meta', prefix,
                          lambda: self.inner.list_meta(prefix))

    def close(self):
        self.inner.close()
