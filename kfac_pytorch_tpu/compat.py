"""JAX API-drift shims, installed at package import.

The codebase targets the current ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` entry point. Older jax releases (<= 0.4.x,
e.g. the 0.4.37 baked into some containers) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` spelling of
the replication/varying-manual-axes checker. Rather than sprinkling
try/except at every call site (the trainer, parallel/, tests, scripts),
``install()`` grafts a translating wrapper onto the ``jax`` module once —
a no-op on a jax that already has ``jax.shard_map``.

Known tradeoff: on legacy jax the graft is visible to EVERY library in
the process — third-party code that feature-detects ``jax.shard_map``
will find the shim (with its check_rep=False policy) instead of a
missing attribute. Accepted here because the alternative (an internal
wrapper import at all ~40 ``jax.shard_map`` call sites across the
package, tests and scripts) buys process isolation only on jax versions
this repo doesn't target, at the cost of diverging from the upstream
spelling everywhere.
"""

import jax


def _wrap_legacy_shard_map(legacy):
    import inspect
    accepts_rep = 'check_rep' in inspect.signature(legacy).parameters

    def shard_map(f, *args, **kwargs):
        kwargs.pop('check_vma', None)
        if accepts_rep:
            # ALWAYS disable the legacy replication checker, even when the
            # caller asked for check_vma=True: the 0.4.x ``check_rep``
            # tracker cannot infer replication through ``lax.cond`` on a
            # psum-derived predicate (the health guard's skip branch,
            # training.py) and rejects valid programs. The modern vma
            # type system is the real check and runs wherever this shim
            # is NOT installed; on legacy jax the P() out_specs still
            # enforce the layout at the XLA level.
            kwargs['check_rep'] = False
        return legacy(f, *args, **kwargs)

    shard_map.__doc__ = legacy.__doc__
    return shard_map


def _legacy_pcast(x, to, axis_name):
    """``lax.pcast(x, to='varying')`` for a jax without the vma type
    system: adding a zero-valued *varying* term (``0 * axis_index``)
    makes the result device-varying under the old shard_map ``check_rep``
    tracker — same effect as pcast, and its transpose leaves cotangents
    local (no inserted psum), which is exactly why capture.make_zero_taps
    casts its taps. Compiles to nothing: XLA folds the zero multiply."""
    if to != 'varying':
        raise NotImplementedError(
            f'legacy pcast shim only supports to="varying", got {to!r}')
    import jax.numpy as jnp
    from jax import lax
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    zero = jnp.zeros((), x.dtype)
    for name in names:
        zero = zero * lax.axis_index(name).astype(x.dtype)
    return x + zero


def install():
    """Idempotent: only patches what this jax is missing."""
    if not hasattr(jax, 'shard_map'):
        from jax.experimental.shard_map import shard_map as legacy
        jax.shard_map = _wrap_legacy_shard_map(legacy)
    if not hasattr(jax.lax, 'pcast'):
        jax.lax.pcast = _legacy_pcast
    if not hasattr(jax.lax, 'axis_size'):
        # psum of the literal 1 is evaluated statically to the axis size
        # (no collective is emitted) on every jax that lacks axis_size
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)
    if not hasattr(jax, 'typeof'):
        # pre-vma avals carry no .vma attribute, so vma-based trace-time
        # guards (capture.check_local_mean_loss) degrade to no-ops —
        # the convention they check is still enforced on current jax
        jax.typeof = lambda x: jax.core.get_aval(x)
