"""Activation / output-gradient capture — the TPU replacement for torch hooks.

The reference captures per-layer inputs ``a`` with forward-pre-hooks and
output-gradients ``g`` with full-backward-hooks (reference:
kfac/kfac_preconditioner_base.py:122-149). JAX has no hooks; this module
implements the functional equivalent:

- **activations**: KFAC-aware layers (``kfac_pytorch_tpu.nn``) ``sow`` their
  input into the ``'kfac_a'`` Flax collection, returned as auxiliary output
  of ``apply`` when that collection is marked mutable.
- **output-gradients**: each layer adds a zero-valued *tap* variable (from
  the ``'kfac_tap'`` collection) to its pre-activation output
  ``y = y + tap``. Differentiating the loss w.r.t. the taps yields exactly
  ``dL/dy`` — the backward-hook ``grad_output`` — in the *same* backward
  pass that produces the parameter gradients.
- **static layer metadata** (kind, dims, conv geometry, param paths) is
  recorded at trace time through a thread-local registry, once, at setup
  (``collect_layer_meta``) — the analogue of ``_register_module_hooks``
  walking ``model.modules()``.

The capture cost is paid only in training steps that update factors
(``steps % fac_update_freq == 0`` gating lives in the trainer, which picks a
compiled step variant without capture otherwise — same semantics as the
hook gating at kfac/kfac_preconditioner_base.py:122-130).
"""

import dataclasses
import threading
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Collection names.
ACTS = 'kfac_a'    # sown layer inputs
TAPS = 'kfac_tap'  # differentiable zero taps on layer outputs


@dataclasses.dataclass(frozen=True)
class LayerMeta:
    """Static description of one KFAC-supported layer.

    The analogue of the reference's ``self.modules`` entries plus the
    geometry that ``ComputeA``/``ComputeG`` read off the torch module
    (reference: kfac/utils.py:78-140).
    """
    name: str                 # '/'.join(path) — stable registry key
    path: Tuple[str, ...]     # module path inside the params pytree
    kind: str                 # 'dense' | 'conv'
    use_bias: bool
    in_dim: int               # true factor-A dim (incl. bias column)
    out_dim: int              # true factor-G dim
    kernel_shape: Tuple[int, ...]   # param 'kernel' shape
    kernel_size: Optional[Tuple[int, int]] = None   # conv only
    strides: Optional[Tuple[int, int]] = None       # conv only
    padding: Optional[Tuple[Tuple[int, int], Tuple[int, int]]] = None  # explicit

    @property
    def grad_shape(self):
        """Matrix-form gradient shape [out_dim, in_dim] (bias col included)."""
        return (self.out_dim, self.in_dim)


# ---------------------------------------------------------------------------
# Trace-time metadata registry
# ---------------------------------------------------------------------------

_REGISTRY = threading.local()


def _registry_active() -> bool:
    return getattr(_REGISTRY, 'active', False)


def report_layer(meta: LayerMeta) -> None:
    """Called by kfac_pytorch_tpu.nn layers during a recorded trace."""
    if _registry_active():
        _REGISTRY.layers[meta.name] = meta


class _record_layers:
    def __enter__(self):
        _REGISTRY.layers = {}
        _REGISTRY.active = True
        return _REGISTRY.layers

    def __exit__(self, *exc):
        _REGISTRY.active = False
        return False


def collect_layer_meta(model, variables, *args, exclude_vocabulary_size=None,
                       **kwargs):
    """Discover KFAC-supported layers by tracing one apply (zero FLOPs).

    Returns ``{name: LayerMeta}`` in call order. ``exclude_vocabulary_size``
    drops dense layers with that output dim — the tied-embedding pre-softmax
    exclusion (reference: kfac_preconditioner_base.py:139-140).
    """
    with _record_layers() as layers:
        jax.eval_shape(
            lambda v: model.apply(v, *args, mutable=True, **kwargs),
            variables)
    metas = dict(layers)
    if exclude_vocabulary_size is not None:
        metas = filter_vocab_head(metas, exclude_vocabulary_size)
    return metas


def filter_vocab_head(metas, vocab_size):
    """Drop the pre-softmax head: the FINAL captured layer, iff it is a
    dense with ``out_dim == vocab_size``. The reference
    (kfac_preconditioner_base.py:139-140) matches by dim at any position;
    that blunt match silently drops interior layers that merely share the
    dim — e.g. a KFACLSTMCell's 4H gate projections when vocab ==
    4*hidden — so here only the last-called layer is excluded and other
    matches are kept with a warning."""
    names = list(metas)
    drop = set()
    if names:
        last = metas[names[-1]]
        if last.kind == 'dense' and last.out_dim == vocab_size:
            drop.add(names[-1])
    interior = [k for k in names if k not in drop
                and metas[k].kind == 'dense'
                and metas[k].out_dim == vocab_size]
    if interior:
        import warnings
        warnings.warn(
            f'layers {interior} match exclude_vocabulary_size={vocab_size} '
            'but are not the trailing pre-softmax head — keeping them '
            'preconditioned', stacklevel=2)
    return {k: m for k, m in metas.items() if k not in drop}


# ---------------------------------------------------------------------------
# Apply / init helpers
# ---------------------------------------------------------------------------

def init(model, rngs, *args, **kwargs):
    """``model.init`` that strips capture collections from the variables.

    During ``init`` all collections are mutable, so taps and sown
    activations would otherwise leak into the returned (checkpointable)
    variables dict.
    """
    variables = model.init(rngs, *args, **kwargs)
    variables = dict(variables)
    variables.pop(ACTS, None)
    variables.pop(TAPS, None)
    return variables


def make_zero_taps(model, variables, *args, axis_name=None, **kwargs):
    """Build the zero-tap pytree for one batch shape via ``eval_shape`` (free
    at trace time). The returned pytree is the differentiable input whose
    gradient is ``{layer: dL/dy}``.

    ``axis_name``: REQUIRED inside shard_map over a data-parallel axis.
    Zero constants are device-invariant, and JAX's vma-aware autodiff psums
    gradients of invariant inputs across the axis — which would silently
    sum per-example output-gradients from different devices. Marking the
    taps varying keeps their gradients local (each device sees its own
    ``g``, the reference's per-rank hook semantics,
    kfac_preconditioner_base.py:127-130).
    """
    shapes = jax.eval_shape(
        lambda v: model.apply(v, *args, mutable=True, **kwargs),
        variables)
    tap_shapes = shapes[1][TAPS]
    taps = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), tap_shapes)
    if axis_name is not None:
        taps = jax.tree.map(lambda t: jax.lax.pcast(t, to='varying',
                                                    axis_name=axis_name),
                            taps)
    return taps


def apply_with_capture(model, variables, *args, taps=None, mutable=(),
                       **kwargs):
    """Run ``model.apply`` with capture active.

    Args:
      variables: full variables dict (params, batch_stats, ...).
      taps: zero-tap pytree from :func:`make_zero_taps`; differentiate the
        loss w.r.t. it to obtain output-gradients.
      mutable: extra mutable collections (e.g. ``['batch_stats']``).

    Returns ``(outputs, acts, other_mutated)`` where ``acts`` is the
    ``{layer: a}`` activation pytree.
    """
    v = dict(variables)
    if taps is not None:
        v[TAPS] = taps
    out, mutated = model.apply(v, *args, mutable=[ACTS] + list(mutable),
                               **kwargs)
    mutated = dict(mutated)
    acts = mutated.pop(ACTS, {})
    return out, acts, mutated


def all_finite(*trees):
    """Scalar bool: every inexact leaf of every tree is finite.

    The reduction feeding the health guard's batch screen (health.py):
    one fused all-reduce over the loss, gradients and captured (a, g)
    pytrees — integer/bool leaves are skipped (trivially finite), empty
    trees are healthy by definition.
    """
    checks = []
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                checks.append(jnp.all(jnp.isfinite(leaf)))
    if not checks:
        return jnp.ones((), bool)
    return jnp.all(jnp.stack(checks))


def check_local_mean_loss(loss, batch, axis_name):
    """Trace-time guard for the LOCAL-mean loss convention (free: reads
    avals only, compiles to nothing).

    The engine's G-factor scaling assumes the loss fed to the capture
    backward is the mean over the LOCAL shard only (the reference's
    per-rank hook semantics: each rank's backward sees that rank's
    per-example output-gradients, kfac_preconditioner_base.py:122-130).
    A loss that was psum/pmean-normalized across the K-FAC world scales
    every cotangent by the shard count, so the preconditioner silently
    depends on the mesh shape — the round-3 postmortem bug
    (scripts/repro_mpd_eigen_orthogonal_axis.py, NOTES.md).

    Detection rides shard_map's varying-manual-axes (vma) tracking: the
    batch varies over the axes its shards differ on; a local-mean loss
    inherits those axes, while a cross-axis pmean/psum strips them.
    Raises ValueError on violation. No-ops where vma is unavailable
    (outside shard_map, or ``check_vma=False`` — but beware:
    ``check_vma=False`` ALSO disables the cross-axis cotangent psums the
    capture relies on, the postmortem's second trap).

    Caveat (ADVICE r4): only a FULLY cross-axis-reduced loss is detected.
    A loss whose *denominator* was globally normalized while the
    numerator still varies — e.g. the masked-LM pattern
    ``local_token_loss_sum / psum(token_count)`` — keeps the batch's vma
    through the varying numerator and passes this guard, yet it violates
    the local-mean convention whenever shards hold unequal token counts
    (each shard's cotangents are scaled by the *global* count instead of
    its own). Normalize by the LOCAL count and let the engine's gradient
    averaging handle the cross-shard mean.
    """
    if axis_name is None:
        return
    axes = {axis_name} if isinstance(axis_name, str) else set(axis_name)

    def vma_of(tree):
        out = set()
        for leaf in jax.tree.leaves(tree):
            out |= set(getattr(jax.typeof(leaf), 'vma', ()) or ())
        return out

    missing = (vma_of(batch) & axes) - vma_of(loss)
    if missing:
        raise ValueError(
            'K-FAC capture loss convention violation: the loss is '
            f'invariant over mesh axes {sorted(missing)} that the batch '
            'varies over — it was psum/pmean-normalized across the '
            'K-FAC world before the capture backward. The convention is '
            'the LOCAL-mean loss (mean over this shard only); average '
            'the GRADIENTS over the K-FAC world instead '
            '(parallel.average_grads). A globally-normalized loss '
            'scales every G factor by the shard count, making the '
            'preconditioner depend on the mesh shape. See README '
            '"Loss conventions" and '
            'scripts/repro_mpd_eigen_orthogonal_axis.py.')


def value_and_grad_with_capture(model, loss_fn, variables, *args,
                                mutable=(), wrt='params', axis_name=None,
                                **kwargs):
    """One fwd+bwd pass returning loss, outputs, param grads, and (a, g).

    The canonical capture entrypoint — the functional equivalent of the
    reference's forward/backward with hooks armed (one ``model(data)`` +
    ``loss.backward()``, kfac_preconditioner_base.py:122-130).

    ``loss_fn(outputs)`` must return a scalar (close over targets) and
    MUST be the LOCAL-mean loss — the mean over this shard's examples
    only, never psum/pmean-normalized across the mesh (see
    :func:`check_local_mean_loss`; ``training.build_train_step`` applies
    that guard automatically, direct harnesses should call it
    themselves).
    Pass ``axis_name`` when calling inside shard_map over a data-parallel
    axis (see :func:`make_zero_taps`); param grads then come back psummed
    over the axis (divide by axis size — ``parallel.average_grads``) while
    ``gs`` stays per-device local.
    Returns ``(loss, outputs, grads, acts, gs, other_mutated)`` with
    ``acts``/``gs`` keyed like the capture collections.
    """
    taps = make_zero_taps(model, variables, *args, axis_name=axis_name,
                          **kwargs)
    params = variables[wrt]
    rest = {k: val for k, val in variables.items() if k != wrt}

    def wrapped(p, t):
        out, acts, mutated = apply_with_capture(
            model, {wrt: p, **rest}, *args, taps=t, mutable=mutable, **kwargs)
        loss = loss_fn(out)
        return loss, (out, acts, mutated)

    (loss, (out, acts, mutated)), (grads, gs) = jax.value_and_grad(
        wrapped, argnums=(0, 1), has_aux=True)(params, taps)
    return loss, out, grads, acts, gs, mutated


# ---------------------------------------------------------------------------
# Pytree path utilities (layer name <-> collection / params subtrees)
# ---------------------------------------------------------------------------

def get_path(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def set_path(tree, path, value):
    """Functionally set ``tree[path] = value`` (dicts only)."""
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = set_path(tree[path[0]], path[1:], value)
    return out


def layer_act(acts, meta: LayerMeta):
    """Pull layer ``meta``'s sown activation out of the capture pytree."""
    return get_path(acts, meta.path)['a']


def layer_g(gs, meta: LayerMeta):
    """Pull layer ``meta``'s output-gradient out of the tap-grad pytree."""
    return get_path(gs, meta.path)['g']


def canonical_padding(in_size, kernel_size, strides, padding):
    """Resolve a Flax-style padding spec to explicit per-dim (lo, hi) pairs
    for the given input spatial size — factor A's im2col must see exactly
    the padding the conv used."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == 'VALID':
            return ((0, 0), (0, 0))
        if p == 'SAME':
            out = []
            for s, k, st in zip(in_size, kernel_size, strides):
                o = -(-s // st)  # ceil
                total = max((o - 1) * st + k - s, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(f'unsupported padding {padding!r}')
    out = []
    for p in padding:
        if isinstance(p, (tuple, list)):
            out.append((int(p[0]), int(p[1])))
        else:
            out.append((int(p), int(p)))
    return tuple(out)
