"""Thin collective wrappers with a degenerate world=1 path.

The reference guards every collective behind ``backend.comm.size() > 1``
(kfac_preconditioner_base.py:204-221) so single-process runs exercise the
full math path with zero comm; passing ``axis_name=None`` here gives the
same property. With an axis name, these lower to XLA collectives scheduled
over ICI (psum / all-gather), which also subsume the reference's tcmm
multi-stream overlap (communicator.cpp:62-72) via XLA async scheduling.
"""

import jax
import jax.numpy as jnp
from jax import lax


def pmean(x, axis_name):
    if axis_name is None:
        return x
    return lax.pmean(x, axis_name)


def psum(x, axis_name):
    if axis_name is None:
        return x
    return lax.psum(x, axis_name)


def all_gather_rows(x, axis_name):
    """Concatenate per-device row blocks along axis 0 (device-major) —
    the owner-broadcast replacement: owners hold their rows, the gather
    replicates all rows everywhere (reference broadcast-from-owner:
    kfac_preconditioner_eigen.py:122-134, inv.py:164-175).

    Implemented as scatter-to-own-offset + psum rather than
    ``lax.all_gather`` so shard_map's varying-manual-axes checker can
    statically prove the result replicated (all_gather output is not
    inferred invariant in current JAX); XLA lowers the masked psum to an
    ICI collective either way.
    """
    if axis_name is None:
        return x
    n = lax.axis_size(axis_name)
    per = x.shape[0]
    full = jnp.zeros((n * per,) + x.shape[1:], x.dtype)
    full = lax.dynamic_update_slice_in_dim(
        full, x, lax.axis_index(axis_name) * per, axis=0)
    return lax.psum(full, axis_name)


def average_grads(grads, axis_name):
    """Data-parallel gradient averaging inside shard_map.

    JAX's vma-aware shard_map already psums the gradient of a varying loss
    w.r.t. replicated (invariant) params — the allreduce the reference gets
    from hvd.DistributedOptimizer / DDP (examples/pytorch_cifar10_resnet.py:
    252-264) is inserted automatically by autodiff. With a per-device
    local-mean loss that psum yields the *sum* of shard means, so dividing
    by the axis size gives the global-batch average (Horovod's
    ``op=Average``). Tap gradients are varying, hence stay local — exactly
    the per-device ``g`` DP-KFAC's factor statistics need.
    """
    if axis_name is None:
        return grads
    n = lax.axis_size(axis_name)
    return jax.tree.map(lambda g: g / n, grads)


def axis_index(axis_name):
    if axis_name is None:
        return jnp.int32(0)
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    if axis_name is None:
        return 1
    return lax.axis_size(axis_name)
