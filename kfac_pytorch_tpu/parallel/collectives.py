"""Thin collective wrappers with a degenerate world=1 path.

The reference guards every collective behind ``backend.comm.size() > 1``
(kfac_preconditioner_base.py:204-221) so single-process runs exercise the
full math path with zero comm; passing ``axis_name=None`` here gives the
same property. With an axis name, these lower to XLA collectives scheduled
over ICI (psum / all-gather), which also subsume the reference's tcmm
multi-stream overlap (communicator.cpp:62-72) via XLA async scheduling.
"""

import jax
import jax.numpy as jnp
from jax import lax


def pmean(x, axis_name):
    if axis_name is None:
        return x
    return lax.pmean(x, axis_name)


def psum(x, axis_name):
    if axis_name is None:
        return x
    return lax.psum(x, axis_name)


def all_gather_rows(x, axis_name):
    """Concatenate per-device row blocks along axis 0 (device-major) —
    the owner-broadcast replacement: owners hold their rows, the gather
    replicates all rows everywhere (reference broadcast-from-owner:
    kfac_preconditioner_eigen.py:122-134, inv.py:164-175).

    Implemented as scatter-to-own-offset + psum rather than
    ``lax.all_gather`` so shard_map's varying-manual-axes checker can
    statically prove the result replicated (all_gather output is not
    inferred invariant in current JAX); XLA lowers the masked psum to an
    ICI collective either way.
    """
    if axis_name is None:
        return x
    n = lax.axis_size(axis_name)
    per = x.shape[0]
    full = jnp.zeros((n * per,) + x.shape[1:], x.dtype)
    full = lax.dynamic_update_slice_in_dim(
        full, x, lax.axis_index(axis_name) * per, axis=0)
    return lax.psum(full, axis_name)


def average_grads(grads, axis_name):
    """Data-parallel gradient averaging inside shard_map.

    JAX's vma-aware shard_map already psums the gradient of a varying loss
    w.r.t. replicated (invariant) params — the allreduce the reference gets
    from hvd.DistributedOptimizer / DDP (examples/pytorch_cifar10_resnet.py:
    252-264) is inserted automatically by autodiff. With a per-device
    local-mean loss that psum yields the *sum* of shard means, so dividing
    by the axis size gives the global-batch average (Horovod's
    ``op=Average``). Tap gradients are varying, hence stay local — exactly
    the per-device ``g`` DP-KFAC's factor statistics need.
    """
    if axis_name is None:
        return grads
    n = lax.axis_size(axis_name)
    return jax.tree.map(lambda g: g / n, grads)


def axis_index(axis_name):
    if axis_name is None:
        return jnp.int32(0)
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    if axis_name is None:
        return 1
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Compression-aware collectives (comm_precision)
# ---------------------------------------------------------------------------
#
# The factor collectives dominate K-FAC's comm budget (reference
# time_breakdown.py ledger: FactorComm 0.300 s / InverseComm 0.146 s at
# 64 ranks); every payload here is either an EMA input (factor stats) or
# a decomposition the pred path damps anyway, so low-precision wire
# formats are safe in a way raw-gradient compression is not. Three wire
# dtypes:
#
#   'fp32'  the exact baseline — every function below is bit-identical
#           to its uncompressed counterpart;
#   'bf16'  cast to bfloat16 on the wire (2x byte reduction), with an
#           error-feedback residual on the reduce path;
#   'int8'  per-leading-row absmax int8 quantization for the gather
#           collectives (4x + a [rows] fp32 scale vector). The REDUCE
#           path floors at bf16 even under 'int8': an XLA all-reduce
#           accumulates in the operand dtype, and int8 partial sums
#           overflow at world >= 2 — see reduce_wire_dtype.
#
# ``axis_name=None`` is always the zero-comm identity path: no cast, no
# quantization, no residual mutation — world=1 stays bit-exact.

WIRE_DTYPES = ('fp32', 'bf16', 'int8')

#: fp32 payload-byte multiplier per wire dtype (int8 ignores the
#: [rows]-scale side channel, which is O(rows) vs the O(rows*D*D) body).
WIRE_COMPRESSION = {'fp32': 1.0, 'bf16': 0.5, 'int8': 0.25}


def check_wire_dtype(comm_precision):
    if comm_precision not in WIRE_DTYPES:
        raise ValueError(f'comm_precision must be one of {WIRE_DTYPES}, '
                         f'got {comm_precision!r}')
    return comm_precision


def reduce_wire_dtype(comm_precision):
    """Wire dtype actually used by the REDUCE collectives: int8 degrades
    to bf16 because an XLA all-reduce accumulates in the operand dtype
    and int8 partial sums overflow (127 * world > 127). The gathers keep
    full int8 — each element has exactly one contributor."""
    return 'bf16' if comm_precision == 'int8' else comm_precision


def quantize_rows(x):
    """Per-leading-row symmetric int8 quantization: ``scale[r] =
    absmax(x[r]) / 127``, ``q = round(x / scale)``. An all-zero row gets
    scale 0 and quantizes (and dequantizes) to exact zeros."""
    absmax = jnp.max(jnp.abs(x), axis=tuple(range(1, x.ndim)))
    scale = absmax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    shaped = scale.reshape(scale.shape + (1,) * (x.ndim - 1))
    shaped_safe = safe.reshape(shaped.shape)
    q = jnp.clip(jnp.round(x / shaped_safe), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_rows(q, scale, dtype=jnp.float32):
    shaped = scale.reshape(scale.shape + (1,) * (q.ndim - 1))
    return q.astype(dtype) * shaped.astype(dtype)


def _lossy(x, comm_precision):
    return (comm_precision != 'fp32'
            and jnp.issubdtype(x.dtype, jnp.floating))


def pmean_wire(x, axis_name, comm_precision='fp32'):
    """pmean over a low-precision wire (no error feedback): the operand
    is cast to the reduce wire dtype, summed by the collective in that
    dtype, and the mean is taken in fp32. Used where no persistent
    residual state exists (E-KFAC scale moments)."""
    if axis_name is None or not _lossy(x, comm_precision):
        return pmean(x, axis_name)
    wire = x.astype(jnp.bfloat16)
    total = lax.psum(wire, axis_name).astype(x.dtype)
    return total / lax.axis_size(axis_name)


def pmean_scatter_ef(x, axis_name, comm_precision, residual, fused=False):
    """Mean-reduce ``x`` across the axis and return THIS device's row
    block of the result (axis 0 is device-major-tiled, the stacked-
    bucket layout of plan.py) — a reduce-scatter, because the factor
    stats' only consumer is each owner's own row slice: an all-reduce
    would ship every row everywhere only to be sliced, ~2x the wire
    traffic and P x the materialized result for nothing.

    Lossy modes add error feedback (EF-SGD lineage: Seide et al. 2014,
    Karimireddy et al. 2019): each device sends ``Q(x + r)`` over the
    wire and carries ``r' = (x + r) - Q(x + r)`` — the quantization
    error re-enters the NEXT reduce instead of being lost, so the
    time-averaged contribution of every device is unbiased. Exactly the
    right shape for the A/G factor statistics, whose consumer is an EMA.
    The wire floors at bf16 even under 'int8' (reduce_wire_dtype): the
    collective must ARITHMETICALLY accumulate, and integer partial sums
    overflow. (Backends without native bf16 reduction — the CPU test
    mesh — promote the bf16 wire back to f32; EF still compensates the
    bf16 rounding the operand went through.)

    Returns ``(local_mean_rows, new_residual)``. ``residual`` may be
    None (fp32 mode) — passed through untouched. ``axis_name=None`` is
    the identity path: ``(x, residual)``, no compression, no residual
    mutation, full rows (P=1 owns everything).

    ``fused=True`` computes the lossy branch's quantize + residual prep
    as ONE Pallas pass (:func:`ops.pallas_capture.ef_quantize`, ISSUE
    19) instead of the three elementwise ops below — same xc/bf16/EF
    algebra, same wire values, so the FactorComm ledger bytes are
    unchanged (pinned by scripts/comm_count.py's ``+pallas`` spec). The
    psum_scatter itself stays out here: fusion moves compute, not wire
    bytes.
    """
    if axis_name is None:
        return x, residual
    n = lax.axis_size(axis_name)
    if not _lossy(x, comm_precision):
        red = lax.psum_scatter(x, axis_name, scatter_dimension=0,
                               tiled=True)
        return red / n, residual
    assert residual is not None, (
        'lossy pmean_scatter_ef requires an error-feedback residual '
        '(init the KFAC state with comm_precision set, see '
        'KFACState.comm_err)')
    if fused:
        from kfac_pytorch_tpu.ops import pallas_capture as _pc
        wire, new_residual = _pc.ef_quantize(
            x, residual, interpret=_pc.interpret_default())
    else:
        xc = x + residual
        wire = xc.astype(jnp.bfloat16)
        new_residual = xc - wire.astype(x.dtype)
    red = lax.psum_scatter(wire, axis_name, scatter_dimension=0,
                           tiled=True).astype(x.dtype)
    return red / n, new_residual


def decomp_exchange_gather(x, axis_name, comm_precision='fp32'):
    """The mesh-sharded decomposition exchange collective: an
    :func:`all_gather_rows_compressed` under the ``kfac.DecompComm``
    named scope, so BOTH legs of the shard round trip (damped cohort
    factors out, decomposed results back) land in their own ledger
    phase — scripts/comm_count.py attributes by op_name scope, and the
    first-match taxonomy puts DecompComm ahead of the
    CommunicateInverse scope these gathers would otherwise inherit
    from the surrounding stagger phase. The byte price is modeled in
    closed form by ``FactorPlan.comm_volume(decomp_shard=...)`` and the
    two must agree byte-for-byte (the COMM_COUNT_ASSERT pin)."""
    with jax.named_scope('kfac.DecompComm'):
        return all_gather_rows_compressed(x, axis_name, comm_precision)


def all_gather_rows_compressed(x, axis_name, comm_precision='fp32'):
    """:func:`all_gather_rows` over a low-precision wire. bf16 ships the
    payload as bitcast uint16 (2 bytes — the integer wire survives every
    backend's float-normalization passes, where a bf16 SUM would be
    promoted back to f32); int8 sends per-leading-row absmax-scaled int8
    plus the [rows] fp32 scale vector (a second, O(rows) gather).
    Non-float payloads and ``axis_name=None`` pass through uncompressed.

    The masked-psum implementation is quantization-exact: every output
    element has exactly ONE non-zero contributor (its owner), so the
    integer sum reconstructs the owner's wire value bit-for-bit — the
    only loss is the owner's local quantization, never accumulation.
    """
    if axis_name is None or not _lossy(x, comm_precision):
        return all_gather_rows(x, axis_name)
    if comm_precision == 'bf16':
        wire = lax.bitcast_convert_type(x.astype(jnp.bfloat16),
                                        jnp.uint16)
        full = lax.bitcast_convert_type(all_gather_rows(wire, axis_name),
                                        jnp.bfloat16)
        return full.astype(x.dtype)
    q, scale = quantize_rows(x)
    qg = all_gather_rows(q, axis_name)
    sg = all_gather_rows(scale, axis_name)
    return dequantize_rows(qg, sg, x.dtype)
