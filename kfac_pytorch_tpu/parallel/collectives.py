"""Thin collective wrappers with a degenerate world=1 path.

The reference guards every collective behind ``backend.comm.size() > 1``
(kfac_preconditioner_base.py:204-221) so single-process runs exercise the
full math path with zero comm; passing ``axis_name=None`` here gives the
same property. With an axis name, these lower to XLA collectives scheduled
over ICI (psum / all-gather), which also subsume the reference's tcmm
multi-stream overlap (communicator.cpp:62-72) via XLA async scheduling.
"""

import jax
import jax.numpy as jnp
from jax import lax


def pmean(x, axis_name):
    if axis_name is None:
        return x
    return lax.pmean(x, axis_name)


def psum(x, axis_name):
    if axis_name is None:
        return x
    return lax.psum(x, axis_name)


def all_gather_rows(x, axis_name):
    """Concatenate per-device row blocks along axis 0 (device-major) —
    the owner-broadcast replacement: owners hold their rows, the gather
    replicates all rows everywhere (reference broadcast-from-owner:
    kfac_preconditioner_eigen.py:122-134, inv.py:164-175)."""
    if axis_name is None:
        return x
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def axis_index(axis_name):
    if axis_name is None:
        return jnp.int32(0)
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    if axis_name is None:
        return 1
    return lax.axis_size(axis_name)
