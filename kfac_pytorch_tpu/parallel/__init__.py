"""Distribution layer: mesh helpers, layer→device scheduling, collectives.

Replaces the reference's Horovod/NCCL/MPI backend (reference:
kfac/backend.py, packages/tcmm/src/communicator.{h,cpp}) with
jax.sharding.Mesh + shard_map + XLA collectives over ICI/DCN.
"""

from kfac_pytorch_tpu.parallel.partition import (
    round_robin_assign,
    balanced_assign,
    block_partition,
)
from kfac_pytorch_tpu.parallel.collectives import (
    pmean,
    psum,
    all_gather_rows,
    average_grads,
    axis_index,
    axis_size,
)
from kfac_pytorch_tpu.parallel.mesh import (
    make_mesh,
    data_parallel_specs,
)
from kfac_pytorch_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from kfac_pytorch_tpu.parallel.moe import ExpertFFN, SwitchMoE
from kfac_pytorch_tpu.parallel.pipeline import gpipe
from kfac_pytorch_tpu.parallel.tp import (
    ColumnParallelDense,
    RowParallelDense,
    TPMultiHeadAttention,
    TPPositionwiseFFN,
    TPEncoderLayer,
)

__all__ = [
    'round_robin_assign', 'balanced_assign', 'block_partition',
    'pmean', 'psum', 'all_gather_rows', 'average_grads', 'axis_index',
    'axis_size',
    'make_mesh', 'data_parallel_specs',
    'ring_attention', 'ulysses_attention',
    'ColumnParallelDense', 'RowParallelDense',
    'TPMultiHeadAttention', 'TPPositionwiseFFN', 'TPEncoderLayer',
    'gpipe', 'ExpertFFN', 'SwitchMoE',
]
