"""Tensor (model) parallelism: Megatron-style column/row-parallel Dense
layers with per-slice K-FAC.

The reference has no tensor parallelism — every layer fits one GPU and
its K-FAC factors are computed on whole-layer matrices. On TPU, sharding
a layer's feature dimension over a mesh axis is first-class (the 'model'
axis of a ('data', 'model') mesh), and K-FAC composes with it cleanly:

- :class:`ColumnParallelDense` — kernel sharded on the OUTPUT dim
  (``P(None, 'model')``): input replicated over ``axis``, output is this
  rank's feature slice. Follow with elementwise ops and a row-parallel
  layer.
- :class:`RowParallelDense` — kernel sharded on the INPUT dim
  (``P('model', None)``): input is the local slice, the partial products
  are ``psum``-reduced over ``axis`` to the full output, and the bias is
  added ONCE after the reduction (replicated, outside the slice's K-FAC
  factor — Megatron's reduce-then-bias).

K-FAC semantics (per-slice block-diagonal): each model-rank runs the
ordinary preconditioner on its LOCAL slice layers with the data axis as
the K-FAC world. The inner Dense's capture taps do exactly the right
thing under shard_map:

- column layer: 'a' = the replicated input (its A factor is the full
  layer's A), 'g' = the local output slice's grads (its G factor is the
  slice-diagonal block of the full G);
- row layer: 'a' = the local input slice, 'g' = the PRE-reduction
  partial output's cotangent — which the psum backward replicates from
  the full dL/dy, so ``dL/dW_slice = a_slice^T g`` is exact.

Preconditioning each slice with (A, G_slice) is the standard
block-diagonal tensor-parallel K-FAC approximation; with one model rank
it degenerates to the exact whole-layer factors. Each rank's K-FAC must
be built over the DATA axis only (``axis_name='data'``): gradients of
sharded params are already local (autodiff inserts no psum for varying
params), and cross-model-rank factor averaging would wrongly mix
distinct diagonal blocks. Pinned by tests/test_tp.py against exact
per-slice oracles.
"""

from typing import Any, Callable, Optional

import flax.linen as linen
import jax
import jax.numpy as jnp

from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.parallel import collectives as coll


class ColumnParallelDense(linen.Module):
    """This rank's output-slice of a Dense whose kernel is sharded on the
    output dim over ``axis``. ``features_per_shard`` is the LOCAL width:
    the global layer has ``features_per_shard * axis_size`` features.

    The input must be replicated over ``axis``; the K-FAC capture of the
    inner Dense then yields the full-layer A factor and the slice-block G
    factor."""
    features_per_shard: int
    axis: Optional[str] = 'model'
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = knn.default_kernel_init
    kfac_enabled: bool = True

    @linen.compact
    def __call__(self, x):
        return knn.Dense(self.features_per_shard, use_bias=self.use_bias,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         kernel_init=self.kernel_init,
                         kfac_enabled=self.kfac_enabled, name='slice')(x)


class RowParallelDense(linen.Module):
    """Full-width output from this rank's input-slice of a Dense whose
    kernel is sharded on the input dim over ``axis``: local partial
    product, ``psum`` over ``axis``, then the (replicated) bias once.

    The bias is a plain param outside the K-FAC factor — it is added
    after the cross-rank reduction, so no single slice owns it (the
    optimizer updates it SGD-style; Megatron semantics). ``axis=None``
    degenerates to a single-slice dense, same as the rest of
    ``parallel/``."""
    features: int
    axis: Optional[str] = 'model'
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = knn.default_kernel_init
    kfac_enabled: bool = True

    @linen.compact
    def __call__(self, x):
        y = knn.Dense(self.features, use_bias=False, dtype=self.dtype,
                      param_dtype=self.param_dtype,
                      kernel_init=self.kernel_init,
                      kfac_enabled=self.kfac_enabled, name='slice')(x)
        y = coll.psum(y, self.axis)
        if self.use_bias:
            bias = self.param('bias', linen.initializers.zeros_init(),
                              (self.features,), self.param_dtype)
            y = y + bias
        return y


class TPMultiHeadAttention(linen.Module):
    """Megatron-sharded post-norm multi-head attention: the HEADS are
    sharded over ``axis`` (``n_head_per_shard`` local heads; global head
    count = local x axis size). Q/K/V projections are column-parallel
    (each rank projects only its heads), the attention math is
    rank-local (heads are independent — zero cross-rank communication),
    and the output projection is row-parallel (one psum rebuilds the
    full d_model output). Mirrors models/transformer.MultiHeadAttention
    (reference examples/transformer/SubLayers.py:11-61) with identical
    math at any shard count — parity pinned by tests/test_tp.py."""
    n_head_per_shard: int
    d_model: int
    d_k: int
    d_v: int
    axis: Optional[str] = 'model'
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.1

    @linen.compact
    def __call__(self, q_in, k_in, v_in, mask=None, train=True):
        from kfac_pytorch_tpu.models.transformer import (
            multi_head_attention_core)
        h, dk, dv = self.n_head_per_shard, self.d_k, self.d_v
        residual = q_in
        q = ColumnParallelDense(h * dk, axis=self.axis, use_bias=False,
                                name='w_q')(q_in)
        k = ColumnParallelDense(h * dk, axis=self.axis, use_bias=False,
                                name='w_k')(k_in)
        v = ColumnParallelDense(h * dv, axis=self.axis, use_bias=False,
                                name='w_v')(v_in)
        if self.seq_axis is not None:
            # sequence-sharded path: the local heads run EXACT ring
            # attention over the seq axis (K/V shards rotate over ICI,
            # parallel/ring_attention.py) — heads x sequence x data, a
            # 3-D ('data', 'seq', 'model') mesh in one block. ``mask``
            # here is the key-padding mask [B, Lk_local] (True=attend)
            # or None; attention-probability dropout is unsupported in
            # the streamed softmax (reference parity holds in the
            # dropout-free regime the bench/eval paths use).
            if train and self.dropout > 0.0:
                raise ValueError('seq_axis attention has no '
                                 'probability-dropout (streamed softmax)'
                                 '; set dropout=0 or train=False')
            if mask is not None and mask.ndim != 2:
                raise ValueError(
                    'seq_axis attention takes a [B, Lk_local] key-padding '
                    f'mask, got ndim={mask.ndim} — full [.., Lq, Lk] '
                    'attention masks are the dense-path contract')
            from kfac_pytorch_tpu.parallel.ring_attention import (
                ring_attention)
            B, Lq = q.shape[0], q.shape[1]
            qh = q.reshape(B, Lq, h, dk).transpose(0, 2, 1, 3)
            kh = k.reshape(B, -1, h, dk).transpose(0, 2, 1, 3)
            vh = v.reshape(B, -1, h, dv).transpose(0, 2, 1, 3)
            o = ring_attention(qh, kh, vh, axis_name=self.seq_axis,
                               causal=self.causal, kv_mask=mask)
            out = o.transpose(0, 2, 1, 3).reshape(B, Lq, h * dv)
        else:
            # the attention-probability dropout must draw an INDEPENDENT
            # mask per model rank (each rank holds different global heads
            # — the dense block draws per-head masks, so sharing one mask
            # across ranks would correlate head groups and make training
            # depend on the shard count); fold the rank index into the
            # rng. The post-projection dropout below runs on the
            # REPLICATED tensor and must keep the shared key (identical
            # mask on every rank).
            drop_rng = None
            if train and self.dropout > 0.0:
                drop_rng = jax.random.fold_in(self.make_rng('dropout'),
                                              coll.axis_index(self.axis))
            att_mask = mask
            if self.causal:
                # causal must mean the same thing on every shard config —
                # the seq path streams it, the dense path applies it here
                cm = jnp.tril(jnp.ones((q_in.shape[1], k_in.shape[1]),
                                       bool))[None, None]
                att_mask = cm if mask is None else jnp.logical_and(mask,
                                                                   cm)
            out = multi_head_attention_core(q, k, v, h, dk, dv, att_mask,
                                            self.dropout, train,
                                            dropout_rng=drop_rng)
        out = RowParallelDense(self.d_model, axis=self.axis,
                               use_bias=False, name='w_o')(out)
        out = linen.Dropout(self.dropout, deterministic=not train)(out)
        return linen.LayerNorm(epsilon=1e-6, name='ln')(out + residual)


class TPPositionwiseFFN(linen.Module):
    """Megatron-sharded post-norm FFN: column-parallel up-projection
    (``d_inner_per_shard`` local hidden units), rank-local relu,
    row-parallel down-projection. Mirrors
    models/transformer.PositionwiseFFN (reference SubLayers.py:135-162);
    w_2's bias is added once after the reduction (Megatron
    reduce-then-bias, outside the slice's K-FAC factor)."""
    d_model: int
    d_inner_per_shard: int
    axis: Optional[str] = 'model'
    dropout: float = 0.1

    @linen.compact
    def __call__(self, x, train=True):
        # KEEP IN SYNC with models/transformer.PositionwiseFFN — same
        # body with the dense layers swapped for the parallel primitives
        # (tests/test_tp.py pins the exact equivalence)
        residual = x
        h = ColumnParallelDense(self.d_inner_per_shard, axis=self.axis,
                                name='w_1')(x)
        h = linen.relu(h)
        h = RowParallelDense(self.d_model, axis=self.axis, name='w_2')(h)
        h = linen.Dropout(self.dropout, deterministic=not train)(h)
        return linen.LayerNorm(epsilon=1e-6, name='ln')(h + residual)


class TPEncoderLayer(linen.Module):
    """models/transformer.EncoderLayer with both sublayers tensor-sharded
    over ``axis`` — the full Megatron transformer block. Per-slice K-FAC
    applies unchanged (the sublayers are built from the Column/Row
    primitives whose factor semantics tests/test_tp.py pins)."""
    d_model: int
    d_inner_per_shard: int
    n_head_per_shard: int
    d_k: int
    d_v: int
    axis: Optional[str] = 'model'
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.1

    @linen.compact
    def __call__(self, x, mask=None, train=True):
        x = TPMultiHeadAttention(self.n_head_per_shard, self.d_model,
                                 self.d_k, self.d_v, axis=self.axis,
                                 seq_axis=self.seq_axis,
                                 causal=self.causal,
                                 dropout=self.dropout,
                                 name='self_attn')(x, x, x, mask, train)
        return TPPositionwiseFFN(self.d_model, self.d_inner_per_shard,
                                 axis=self.axis, dropout=self.dropout,
                                 name='ffn')(x, train)


def axis_rules(column=('w_q', 'w_k', 'w_v', 'w_1'), row=('w_o', 'w_2')):
    """Mesh-plan ``LayerAxisRule`` pair for column/row-parallel layers
    named here (the module names WRAPPING the inner capture Dense,
    e.g. ``column=('l1',)`` for ``ColumnParallelDense(name='l1')``).

    Defaults are this module's Megatron sublayer names, so
    ``tp.axis_rules()`` covers :class:`TPEncoderLayer` stacks as-is.
    Column-parallel: A joins the tensor-axis reduce (replicated input);
    row-parallel: G does (psum-replicated cotangent). See
    ``meshplan.rules`` for the full derivation.
    """
    from kfac_pytorch_tpu.meshplan import rules as _mr
    out = []
    if column:
        out.append(_mr.column_parallel_rule(tuple(column)))
    if row:
        out.append(_mr.row_parallel_rule(tuple(row)))
    return tuple(out)
