"""Layer/factor → device scheduling (host-side, static).

The reference schedules preconditioner work round-robin
(kfac_preconditioner_inv.py:62-77, with the factor-wise interleaved variant
at kfac_preconditioner_eigen.py:75-94) and ships a smarter load-balanced
block partition as research code (scripts/dp_block_partition.py:11-76).
Here both are first-class policies; the assignment decides the row order of
the stacked factor buckets, so "rank owns layer" becomes "mesh index owns
stacked-array rows".
"""

import numpy as np


def round_robin_assign(n_items, num_devices):
    """item i → device i % P. Parity: kfac_preconditioner_inv.py:62-77 (and,
    applied to an interleaved A/G slot sequence, eigen.py:75-94)."""
    return np.arange(n_items, dtype=np.int64) % num_devices


def balanced_assign(costs, num_devices):
    """Greedy longest-processing-time assignment: sort by cost descending,
    place each item on the least-loaded device.

    The practical equivalent of the optimal bottleneck block partition the
    reference prototypes (scripts/dp_block_partition.py:11-76) — LPT is
    within 4/3 of optimal makespan and, unlike the contiguous block
    partition, is order-free (row order inside buckets is ours to choose).
    """
    costs = np.asarray(costs, dtype=np.float64)
    owners = np.zeros(len(costs), dtype=np.int64)
    load = np.zeros(num_devices, dtype=np.float64)
    for i in np.argsort(-costs, kind='stable'):
        d = int(np.argmin(load))
        owners[i] = d
        load[d] += costs[i]
    return owners


def block_partition(costs, num_devices):
    """Optimal contiguous bottleneck partition via dynamic programming.

    Functional parity with the reference's research scheduler
    (scripts/dp_block_partition.py:11-76): split an ordered cost list into
    ``num_devices`` contiguous blocks minimizing the max block sum. Returns
    an owner array. Useful when assignment must preserve layer order.
    """
    costs = np.asarray(costs, dtype=np.float64)
    n = len(costs)
    p = min(num_devices, n) if n else num_devices
    prefix = np.concatenate([[0.0], np.cumsum(costs)])
    # dp[k][i]: min bottleneck splitting first i items into k blocks
    dp = np.full((p + 1, n + 1), np.inf)
    cut = np.zeros((p + 1, n + 1), dtype=np.int64)
    dp[0, 0] = 0.0
    for k in range(1, p + 1):
        for i in range(1, n + 1):
            for j in range(k - 1, i):
                cand = max(dp[k - 1, j], prefix[i] - prefix[j])
                if cand < dp[k, i]:
                    dp[k, i] = cand
                    cut[k, i] = j
    owners = np.zeros(n, dtype=np.int64)
    i = n
    for k in range(p, 0, -1):
        j = cut[k, i]
        owners[j:i] = k - 1
        i = j
    return owners
