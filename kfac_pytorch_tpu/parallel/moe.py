"""Expert parallelism: Switch-style top-1 mixture-of-experts with
``all_to_all`` token dispatch over an 'expert' mesh axis.

The reference has no MoE/expert parallelism. The TPU-native shape: one
expert FFN per mesh rank; each rank's local tokens are routed by a
(replicated) top-1 gate, packed into per-expert slots, exchanged with
TWO ``lax.all_to_all``s (dispatch and return — the canonical EP
collective pattern), processed by the rank-local expert, and combined
scaled by the gate probability.

K-FAC composes per-expert: the expert's Dense layers are ordinary
capture layers, so each rank's factors are computed from the token batch
ITS expert actually processed — owner-local (DP-KFAC-style) semantics
over the expert axis, with the data axis as the K-FAC world exactly as
in ``parallel/tp.py``. Padded (empty) slots are zero rows: they add
nothing to the G moments or the kernel block of A, but the bias-
augmentation column (ops.compute_a_dense appends ones) gives each empty
slot a unit contribution to A's bias-bias entry — so run EP K-FAC with
capacity sized near the actual load, or the bias coordinate of the
preconditioner is damped proportionally to the empty-slot fraction.

Capacity: ``capacity`` slots per (local rank -> expert) pair. With
``capacity = local token count`` no token can ever drop and the layer is
EXACTLY the dense computation ``y_t = p_t * FFN_{e_t}(x_t)`` (pinned by
tests/test_moe.py); smaller capacities drop overflow tokens to zero
output (standard Switch behavior, the memory/compute knob).
"""

from typing import Optional

import flax.linen as linen
import jax
import jax.numpy as jnp
from jax import lax

from kfac_pytorch_tpu import nn as knn


class ExpertFFN(linen.Module):
    """One expert: Dense -> gelu -> Dense, both K-FAC capture layers."""
    d_model: int
    d_hidden: int

    @linen.compact
    def __call__(self, x):
        h = jax.nn.gelu(knn.Dense(self.d_hidden, name='w_in')(x))
        return knn.Dense(self.d_model, name='w_out')(h)


class SwitchMoE(linen.Module):
    """Top-1 routed MoE over ``axis`` (one expert per rank).

    Input ``[T_local, d_model]`` tokens (flatten batch x sequence first);
    output the same shape. The gate is a replicated plain Dense (not
    K-FAC-captured — its K-FAC treatment would need the router's
    load-balancing loss machinery; SGD-updated like LayerNorms). Returns
    ``(y, aux)`` with ``aux['gate_probs']`` for an optional
    load-balancing loss.

    ``axis=None`` degenerates to a single local expert (world=1 path,
    same convention as the rest of ``parallel/``).

    Gradient scaling (ADVICE r3): under the local-mean-loss convention
    (average over the DATA axis only — README "Loss conventions") the
    expert axis ALSO shards tokens, so the cross-axis gradient psum sums
    the ``ne`` per-shard means: gate and expert gradients (and their G
    factors) carry an extra factor of ``axis_size('expert')`` relative
    to a dense global-token-mean run. Consistent across mesh shapes
    (pinned by tests/test_moe.py), but a dense-tuned learning rate does
    NOT transfer — divide lr by the expert-axis size (or scale the loss
    by ``1/ne``) when porting hyperparameters from a dense run."""
    d_model: int
    d_hidden: int
    capacity: int
    axis: Optional[str] = 'expert'

    @linen.compact
    def __call__(self, x):
        T, d = x.shape
        n = 1 if self.axis is None else lax.axis_size(self.axis)
        C = self.capacity
        logits = linen.Dense(n, name='gate')(x)          # [T, n]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)              # [T]
        p_top = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

        # slot position of each token within its expert's local buffer
        onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)   # [T, n]
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1         # [T, n]
        slot = pos.max(axis=-1)                               # [T]
        keep = slot < C                                       # overflow drops
        # dispatch tensor [T, n, C]: token t -> (expert e_t, slot)
        disp = (jax.nn.one_hot(expert, n)[:, :, None]
                * jax.nn.one_hot(jnp.where(keep, slot, 0), C)[:, None, :]
                * keep[:, None, None])
        xbuf = jnp.einsum('tec,td->ecd', disp, x)             # [n, C, d]

        if self.axis is not None:
            # dispatch all_to_all: rank r sends xbuf[e] to rank e and
            # receives every rank's buffer for ITS expert -> [n, C, d]
            # (n source ranks x C slots each)
            xbuf = lax.all_to_all(xbuf, self.axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        ybuf = ExpertFFN(self.d_model, self.d_hidden,
                         name='expert')(xbuf.reshape(-1, d))
        ybuf = ybuf.reshape(-1, C, d)
        if self.axis is not None:
            # return all_to_all: send each source rank its tokens back
            ybuf = lax.all_to_all(ybuf, self.axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        y = jnp.einsum('tec,ecd->td', disp, ybuf)
        return y * p_top[:, None], {'gate_probs': probs, 'dropped': ~keep}


def axis_rules(experts=('expert',)):
    """Mesh-plan rule marking these modules' factors expert-LOCAL state:
    each rank's expert is a different set of parameters, so its factor
    statistics must never reduce over the expert axis — zero factor
    bytes on that axis (the DP-KFAC owner-local trick), which
    ``MeshFactorPlan.comm_volume`` accounts and scripts/comm_count.py
    asserts against the HLO. Default matches :class:`SwitchMoE`'s
    rank-local ``ExpertFFN(name='expert')``.
    """
    from kfac_pytorch_tpu.meshplan import rules as _mr
    return (_mr.expert_local_rule(tuple(experts)),)
