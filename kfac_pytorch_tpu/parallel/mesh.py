"""Mesh construction helpers — the launch/cluster layer, TPU-style.

The reference establishes the process group via mpirun + Horovod/torchrun
(launch_horovod.sh:32, kfac/backend.py:29-48). On TPU the equivalent is one
jax.sharding.Mesh over all devices (multi-host via jax.distributed); data
parallelism is a mesh axis, not a process abstraction.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices=None, axis_name='batch', devices=None):
    """1-D data-parallel mesh over the first ``num_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def data_parallel_specs(axis_name='batch'):
    """(replicated, batch-sharded) PartitionSpecs for the common case."""
    return P(), P(axis_name)


def shard_batch(mesh, axis_name, batch):
    """Place a host batch with its leading axis sharded over the mesh —
    the DistributedSampler equivalent (reference:
    examples/pytorch_cifar10_resnet.py:180-192)."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
