"""Mesh construction helpers — the launch/cluster layer, TPU-style.

The reference establishes the process group via mpirun + Horovod/torchrun
(launch_horovod.sh:32, kfac/backend.py:29-48). On TPU the equivalent is one
jax.sharding.Mesh over all devices (multi-host via jax.distributed); data
parallelism is a mesh axis, not a process abstraction.
"""

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_initialize_distributed(retry=None, coordinator_address=None,
                                 num_processes=None, process_id=None):
    """Initialize jax.distributed for multi-host pods when the launcher
    exported the coordination env (launch_tpu.sh) — the process-boundary
    replacement for mpirun/hostfiles (reference: launch_horovod.sh:32).
    No-op on single host.

    The explicit ``coordinator_address`` / ``num_processes`` /
    ``process_id`` arguments override the environment — the elastic
    shrink path (``resilience.elastic``) rebuilds the mesh with a new
    coordinator and a reduced process count without re-exec'ing through
    the launcher.

    The initialize call runs under ``call_with_retry``: on a pod-wide
    restart every host races the coordinator's listener coming back up,
    and the losers used to crash their first relaunch attempt with a
    connection error instead of backing off. ``retry`` is a
    ``resilience.RetryPolicy`` (default: 5 attempts, 1s base backoff,
    retrying connection-shaped failures including the RuntimeError jax
    wraps them in); pass ``retry=False`` to fail fast.
    """
    addr = (coordinator_address
            or os.environ.get('JAX_COORDINATOR_ADDRESS'))
    if not addr or not os.environ.get('KFAC_TPU_MULTIHOST'):
        return False
    nproc = (num_processes if num_processes is not None
             else int(os.environ['JAX_NUM_PROCESSES']))
    pid = (process_id if process_id is not None
           else int(os.environ['JAX_PROCESS_ID']))

    def _init():
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=nproc, process_id=pid)

    if retry is False:
        _init()
        return True
    from kfac_pytorch_tpu.resilience.retry import (RetryError,
                                                   RetryPolicy,
                                                   call_with_retry)
    on_retry = None
    if retry is None:
        retry = RetryPolicy(
            attempts=5, base_delay=1.0, max_delay=15.0,
            retry_on=(OSError, TimeoutError, ConnectionError,
                      RuntimeError))

        def on_retry(e, attempt, delay):
            # jax wraps the coordinator race in a bare RuntimeError, but
            # so are PERMANENT failures ("already initialized", a
            # malformed address) — retry only the connection-shaped
            # ones, or every host burns the whole backoff budget
            # re-raising the same config error
            if isinstance(e, RuntimeError) and not isinstance(
                    e, (OSError, TimeoutError)):
                msg = str(e).lower()
                if not any(t in msg for t in
                           ('connect', 'coordinator', 'unavailable',
                            'timed out', 'deadline')):
                    raise RetryError(msg)

    call_with_retry(_init, policy=retry, on_retry=on_retry,
                    label=f'jax.distributed.initialize({addr})',
                    counter='dist_init_retries')
    return True


def make_mesh(num_devices=None, axis_name='batch', devices=None):
    """1-D data-parallel mesh over the first ``num_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def data_parallel_specs(axis_name='batch'):
    """(replicated, batch-sharded) PartitionSpecs for the common case."""
    return P(), P(axis_name)


def shard_batch(mesh, axis_name, batch):
    """Place a host batch with its leading axis sharded over the mesh —
    the DistributedSampler equivalent (reference:
    examples/pytorch_cifar10_resnet.py:180-192)."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def make_composed_mesh(spec, devices=None):
    """Mesh for a ``'dp2xtp2'``-style composed spec (meshplan grammar).

    Axis order/names follow the spec tokens (dp->'data', sp->'seq',
    tp->'model', ep->'expert', pp->'stage' unless renamed with
    ``=<name>``), so the returned mesh lines up with the
    ``MeshFactorPlan`` built from the same spec. Returns ``(mesh, axes)``
    — the parsed ``AxisSpec`` tuple is what ``KFAC(mesh_axes=...)``
    and ``build_mesh_plan`` take.
    """
    from kfac_pytorch_tpu.meshplan import axes as axes_mod
    axes = axes_mod.parse_mesh_spec(spec)
    shape = axes_mod.mesh_shape(axes)
    need = axes_mod.total_devices(axes)
    if devices is None:
        devices = jax.devices()
    if len(devices) < need:
        raise ValueError(
            f'mesh spec {axes_mod.format_mesh_spec(axes)!r} needs '
            f'{need} devices, have {len(devices)}')
    arr = np.asarray(devices[:need]).reshape(shape)
    return Mesh(arr, tuple(a.name for a in axes)), axes
