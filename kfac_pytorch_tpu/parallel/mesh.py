"""Mesh construction helpers — the launch/cluster layer, TPU-style.

The reference establishes the process group via mpirun + Horovod/torchrun
(launch_horovod.sh:32, kfac/backend.py:29-48). On TPU the equivalent is one
jax.sharding.Mesh over all devices (multi-host via jax.distributed); data
parallelism is a mesh axis, not a process abstraction.
"""

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_initialize_distributed():
    """Initialize jax.distributed for multi-host pods when the launcher
    exported the coordination env (launch_tpu.sh) — the process-boundary
    replacement for mpirun/hostfiles (reference: launch_horovod.sh:32).
    No-op on single host."""
    addr = os.environ.get('JAX_COORDINATOR_ADDRESS')
    if not addr or not os.environ.get('KFAC_TPU_MULTIHOST'):
        return False
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ['JAX_NUM_PROCESSES']),
        process_id=int(os.environ['JAX_PROCESS_ID']))
    return True


def make_mesh(num_devices=None, axis_name='batch', devices=None):
    """1-D data-parallel mesh over the first ``num_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def data_parallel_specs(axis_name='batch'):
    """(replicated, batch-sharded) PartitionSpecs for the common case."""
    return P(), P(axis_name)


def shard_batch(mesh, axis_name, batch):
    """Place a host batch with its leading axis sharded over the mesh —
    the DistributedSampler equivalent (reference:
    examples/pytorch_cifar10_resnet.py:180-192)."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
