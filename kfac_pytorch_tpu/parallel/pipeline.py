"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis, differentiated THROUGH the collective.

The reference has no pipeline parallelism (its models fit one GPU). On
TPU the natural implementation is SPMD: every rank runs the same
``lax.scan`` of ticks; at tick ``t`` rank ``i`` processes microbatch
``t - i`` (the GPipe schedule, bubbles included), and activations hop to
the next stage with ONE ``lax.ppermute`` per tick (neighbor traffic —
rides a single ICI hop on a ring mesh). The backward pass needs no
hand-written schedule at all: ``jax.grad`` of a ppermute is the reversed
ppermute, so differentiating the forward scan IS the reverse pipeline —
cotangents hop backward stage-to-stage with the same bubble structure.

Scope: homogeneous stages (equal activation widths between stages — each
stage is e.g. one transformer block or one equal-width MLP segment) and
last-stage outputs. Bubble ticks compute garbage that is masked out of
the collected outputs, so their cotangents are exactly zero and
gradients equal the unpipelined model's (pinned by
tests/test_pipeline.py against the sequential composition).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_apply: Callable, params_local, x, n_microbatches,
          axis_name):
    """Run ``n_microbatches`` through an S-stage pipeline over
    ``axis_name``; must be called inside shard_map over that axis.

    Args:
      stage_apply: ``stage_apply(params_local, h) -> h`` — THIS rank's
        stage. Activation shape must be identical between stages.
      params_local: this rank's stage parameters (pytree; sharded over
        ``axis_name`` by the caller's in_specs).
      x: ``[B, ...]`` the full local batch (consumed at stage 0; other
        ranks ignore it). B must divide by ``n_microbatches``.
      n_microbatches: M >= 1; the bubble fraction is (S-1)/(M+S-1).
      axis_name: the pipeline mesh axis.

    Returns ``[B, ...]`` outputs in input order, valid on the LAST stage
    rank (other ranks return zeros — psum or gather as needed).
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    assert B % M == 0, (B, M)
    mb = x.reshape(M, B // M, *x.shape[1:])
    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    # the carry dtype must be the stage OUTPUT dtype (a bf16 stage fed
    # through an f32 carry would mismatch lax.scan's carry type): fix it
    # abstractly, and confirm the stage is a dtype fixed point
    out = jax.eval_shape(stage_apply, params_local,
                         jax.ShapeDtypeStruct(mb[0].shape, mb[0].dtype))
    out = jax.eval_shape(stage_apply, params_local,
                         jax.ShapeDtypeStruct(out.shape, out.dtype))
    assert out.shape == mb[0].shape, (out.shape, mb[0].shape)
    dt = out.dtype

    def tick(h_in, t):
        # stage 0 injects microbatch t (clamped; ticks >= M re-inject the
        # last microbatch and are masked out of the outputs), later
        # stages consume the activation that hopped in last tick
        x_t = lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), axis=0,
                                       keepdims=False).astype(dt)
        h = jnp.where(idx == 0, x_t, h_in)
        h = stage_apply(params_local, h)
        # collect at the last stage: tick t completes microbatch t-(S-1)
        valid = jnp.logical_and(idx == S - 1,
                                jnp.logical_and(t >= S - 1, t <= M + S - 2))
        out_t = jnp.where(valid, h, 0)
        h_next = lax.ppermute(h, axis_name, fwd_perm)
        return h_next, out_t

    # the carry must hold the full varying set of the loop (x's axes,
    # e.g. 'data', AND the stage params' pipeline axis) so the scan
    # carry type is stable under shard_map's vma checker: derive the
    # zeros from the input AND every params leaf (a single leaf could
    # miss axes that only other leaves vary over; zero leaves also keeps
    # a stateless stage working)
    h0 = (0 * mb[0]).astype(dt)
    h0 = h0 + sum(jax.tree.leaves(jax.tree.map(
        lambda p: (0 * p.reshape(-1)[0]).astype(dt), params_local)),
        jnp.zeros((), dt))
    _, outs = lax.scan(tick, h0, jnp.arange(M + S - 1))
    # outs: [T, Bm, ...]; microbatch m sits at tick m + S - 1
    outs = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
    return outs.reshape(B, *outs.shape[2:])
