"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context support at all (SURVEY.md §5.7 — its max
sequence length is 384 and K-FAC averages the sequence axis away). This
framework makes long sequences first-class on TPU: shard the *sequence*
axis of a transformer over a mesh axis and compute exact attention with

- **ring attention** (:func:`ring_attention`): K/V shards rotate around
  the mesh axis via ``lax.ppermute`` (one ICI hop per step) while each
  device streams softmax online (flash-style running max / normalizer),
  so no device ever materializes the full [L, L] score matrix or the full
  K/V. Communication overlaps with the block matmuls under XLA's async
  collective scheduling. Memory per device: O(L_local * L_block).
- **Ulysses all-to-all** (:func:`ulysses_attention`): two
  ``lax.all_to_all``s swap the sequence shard for a *head* shard, run
  dense local attention on the full sequence for H/n heads, and swap
  back. Cheaper at moderate L (2 collectives instead of n-1 permutes) as
  long as the head count divides the axis.

Both are exact (match single-device softmax attention), jit-safe
(``lax.fori_loop``), support causal masking and key-padding masks, and
degenerate to plain attention when ``axis_name`` is None — the same
world=1 zero-comm property as the rest of ``parallel/``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


#: 'auto' forward crossover: measured on a real v5e chip (2026-07-31,
#: B=1 H=8 D=64 causal fwd+bwd, logs/onchip/queue_0731_0346.summary) the
#: XLA blockwise path wins below this key length (8k: 43.5 ms vs 59.4;
#: 16k: 103.6 vs 180.9) while at 32k the Pallas kernel is the only path
#: that compiles at all (XLA: remote-compile failure; Pallas: 657 ms).
#: Lk is a static shape, so the choice is made at trace time — the same
#: policy shape as ops.pallas_attention.AUTO_BWD_PALLAS_MIN_LK.
AUTO_FWD_PALLAS_MIN_LK = 32768


def _default_block_impl():
    """'auto' on TPU (length-gated XLA/Pallas, see :func:`_fwd_impl_for`),
    'xla' elsewhere (interpret mode is for tests). KFAC_ATTN_IMPL
    overrides ('auto' | 'xla' | 'pallas' | 'pallas_interpret')."""
    import os
    env = os.environ.get('KFAC_ATTN_IMPL')
    if env:
        return env
    return 'auto' if jax.default_backend() == 'tpu' else 'xla'


def _fwd_impl_for(impl, lk):
    """Resolve the forward block implementation; 'auto' picks by the
    (static) key length of this block — XLA blockwise below the measured
    v5e crossover, the Pallas flash kernel at/above it."""
    if impl not in ('auto', 'xla', 'pallas', 'pallas_interpret'):
        raise ValueError(f'KFAC_ATTN_IMPL={impl!r}: expected '
                         "'auto', 'xla', 'pallas' or 'pallas_interpret'")
    if impl == 'auto':
        return 'pallas' if lk >= AUTO_FWD_PALLAS_MIN_LK else 'xla'
    return impl


def interpreted_attention_active():
    """True when attention blocks resolve to the Pallas interpreter.

    The interpreter's block-index machinery cannot evaluate the kernel's
    scalar-prefetch meta once shard_map's varying-manual-axes checker has
    tagged it (per-device ring offsets vary over the seq axis), so any
    shard_map enclosing interpreted attention must pass check_vma=False
    — training.build_train_step consults this. TPU lowering is unaffected
    (meta rides SMEM)."""
    return _default_block_impl() == 'pallas_interpret'


def _block_attn_dispatch(q, k, v, q_start, k_start, causal, kv_mask,
                         scale, block_impl):
    """One streaming block through the selected implementation.

    'xla': plain jnp ops (materializes the [Lq, Lk] block scores and lets
    XLA fuse); 'pallas'/'pallas_interpret': the fused flash kernel
    (ops/pallas_attention.py), which never materializes scores in HBM;
    'auto': length-gated choice between them (:func:`_fwd_impl_for`).
    Both return identical (m, l, pv).
    """
    block_impl = _fwd_impl_for(block_impl, k.shape[2])
    if block_impl == 'xla':
        bias = _bias_for_block(q_start, k_start, q.shape[2], k.shape[2],
                               causal, kv_mask)
        return _block_attn(q, k, v, bias, scale)
    from kfac_pytorch_tpu.ops.pallas_attention import flash_block_attn
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    # pad sequence lengths up to the kernel's tile grid (<=128: multiple
    # of 8; >128: multiple of 128). Padded keys are masked out (exact:
    # their exp terms are 0); padded query rows are sliced off — and
    # jnp.pad's VJP slices the cotangents back, so gradients stay exact.
    pad_to = lambda n: -(-n // 8) * 8 if n <= 128 else -(-n // 128) * 128
    Lqp, Lkp = pad_to(Lq), pad_to(Lk)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, Lqp - Lq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, Lkp - Lk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, Lkp - Lk), (0, 0)))
    maskf = (jnp.ones((B, Lk), jnp.float32) if kv_mask is None
             else kv_mask.astype(jnp.float32))
    maskf = jnp.pad(maskf, ((0, 0), (0, Lkp - Lk)))  # pad keys masked
    fold = lambda x: x.reshape(B * H, *x.shape[2:])
    maskf = jnp.repeat(maskf, H, axis=0)
    starts = jnp.stack([jnp.asarray(q_start, jnp.int32),
                        jnp.asarray(k_start, jnp.int32)])
    m, l, pv = flash_block_attn(
        fold(qp), fold(kp), fold(vp), maskf, starts, scale, causal,
        block_impl == 'pallas_interpret')
    unfold = lambda x: x.reshape(B, H, *x.shape[1:])[:, :, :Lq]
    return unfold(m), unfold(l), unfold(pv)


def _block_attn(q, k, v, bias, scale):
    """One streaming block: scores, masked, unnormalized softmax pieces.

    q: [B, H, Lq, D]; k/v: [B, H, Lk, D]; bias: broadcastable to
    [B, H, Lq, Lk] additive (-inf to mask). Returns (m, p, pv) with
    m: [B, H, Lq] block row max, p: exp(s - m), pv: p @ v.
    """
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    # the running max is a pure numerical shift: softmax is invariant to
    # it, so it must be a constant to autodiff (a half-stop-gradiented
    # max would corrupt the backward pass)
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1))
    p = jnp.exp(s - m[..., None])
    pv = jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(p.dtype),
                    preferred_element_type=jnp.float32)
    return m, p.sum(axis=-1), pv


def _merge(o, l, m, pv_j, l_j, m_j):
    """Merge one block's (pv, l, m) into running (o, l, m) — the online
    softmax recurrence."""
    m_new = jnp.maximum(m, m_j)
    c = jnp.exp(m - m_new)
    c_j = jnp.exp(m_j - m_new)
    o = o * c[..., None] + pv_j * c_j[..., None]
    l = l * c + l_j * c_j
    return o, l, m_new


def ring_attention(q, k, v, axis_name, causal=False, kv_mask=None,
                   scale=None, block_impl=None):
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Args:
      q: [B, H, Lq_local, D] local query shard.
      k, v: [B, H, Lk_local, D] local key/value shards (same sequence
        sharding as q).
      axis_name: mesh axis the sequence is sharded over (None = 1 device).
      causal: causal masking in *global* sequence positions.
      kv_mask: optional [B, Lk_local] bool, True = attend (key padding).
      scale: score scale; default 1/sqrt(D).

    Returns [B, H, Lq_local, D] — bitwise the same math as softmax
    attention over the gathered sequence.
    """
    scale = scale or (q.shape[-1] ** -0.5)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    dtype = jnp.float32

    block_impl = block_impl or _default_block_impl()
    if axis_name is None:
        m, l, pv = _block_attn_dispatch(q, k, v, 0, 0, causal, kv_mask,
                                        scale, block_impl)
        return (pv / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # accumulators are derived from q (zeroed) rather than fresh constants
    # so they inherit q's full varying-manual-axes set — shard_map's vma
    # checker requires the loop carry to keep a stable type even when the
    # inputs also vary over other mesh axes (e.g. a 'data' axis)
    zq = (q * 0).astype(dtype)
    o = jnp.zeros((B, H, Lq, D), dtype) + zq
    l = zq.sum(axis=-1)
    m = l + _NEG_INF
    # carry the padding mask as f32 (collectives over bool are unreliable)
    zk = (k[:, 0, :, 0] * 0).astype(dtype)
    kv_mask = (1.0 + zk if kv_mask is None
               else kv_mask.astype(dtype) + zk)

    def body(t, carry):
        o, l, m, k_t, v_t, mask_t = carry
        src = (me - t) % n  # which global shard this K/V block came from
        m_j, l_j, pv_j = _block_attn_dispatch(
            q, k_t, v_t, me * Lq, src * Lk, causal, mask_t > 0.5, scale,
            block_impl)
        o, l, m = _merge(o, l, m, pv_j, l_j, m_j)
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        mask_t = lax.ppermute(mask_t, axis_name, perm)
        return o, l, m, k_t, v_t, mask_t

    o, l, m, *_ = lax.fori_loop(0, n, body, (o, l, m, k, v, kv_mask))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _bias_for_block(q_start, k_start, Lq, Lk, causal, kv_mask):
    """Additive bias [*, Lq, Lk] combining global-position causal masking
    and the key-padding mask for one K/V block."""
    bias = None
    if causal:
        qpos = q_start + jnp.arange(Lq)[:, None]
        kpos = k_start + jnp.arange(Lk)[None, :]
        bias = jnp.where(qpos >= kpos, 0.0, _NEG_INF)[None, None]
    if kv_mask is not None:
        pad = jnp.where(kv_mask, 0.0, _NEG_INF)[:, None, None, :]
        bias = pad if bias is None else bias + pad
    return bias


def ulysses_attention(q, k, v, axis_name, causal=False, kv_mask=None,
                      scale=None, block_impl=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Same contract as :func:`ring_attention` but requires ``H`` divisible
    by the axis size: all-to-all converts the sequence shard into a head
    shard, attention runs dense over the full sequence for H/n heads,
    and a second all-to-all restores sequence sharding.
    """
    scale = scale or (q.shape[-1] ** -0.5)
    if axis_name is None:
        return ring_attention(q, k, v, None, causal=causal,
                              kv_mask=kv_mask, scale=scale,
                              block_impl=block_impl)
    n = lax.axis_size(axis_name)
    B, H, Lq, D = q.shape
    if H % n:
        raise ValueError(f'ulysses needs heads ({H}) % axis ({n}) == 0')

    # [B, H, L_local, D] -> [B, H/n, L_global, D]
    swap = functools.partial(lax.all_to_all, axis_name=axis_name,
                             split_axis=1, concat_axis=2, tiled=True)
    unswap = functools.partial(lax.all_to_all, axis_name=axis_name,
                               split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = swap(q), swap(k), swap(v)
    maskg = None
    if kv_mask is not None:
        maskg = lax.all_gather(kv_mask.astype(jnp.float32), axis_name,
                               axis=1, tiled=True) > 0.5
    m, l, pv = _block_attn_dispatch(
        qg, kg, vg, 0, 0, causal, maskg,
        scale, block_impl or _default_block_impl())
    out = (pv / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    return unswap(out)
