"""The K-FAC preconditioner facade: four variants behind one engine.

Reference surface parity (kfac/__init__.py:8-16 and the four
kfac_preconditioner_*.py classes) via three orthogonal engine switches:

  variant       stats_reduce   method      comm_mode
  ----------    ------------   ---------   -------------------------------
  inverse       pmean (MPD)    cholesky    'pred' (default) or 'inverse'
                                           per communicate_inverse_or_not
                                           (inv.py:41)
  eigen         pmean (MPD)    eigh        'inverse' (forced, eigen.py:52)
  inverse_dp    local  (DP)    cholesky    'pred' (forced, inv_dp.py:52)
  eigen_dp      local  (DP)    eigh        'pred' (forced — the flagship,
                                           train_cifar10.sh:19)

Unlike the reference's stateful ``torch.optim.Optimizer`` subclass, the
preconditioner is a pure-functional transformation: ``step`` maps
``(state, grads, captured stats) -> (preconditioned grads, state)`` and is
designed to be traced inside jit / shard_map. Host-side knobs
(``fac_update_freq`` / ``kfac_update_freq`` / ``damping``) select static
step variants and feed traced scalars — the KFACParamScheduler mutates them
without recompilation.
"""

import dataclasses
from typing import Any, Dict, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from kfac_pytorch_tpu import engine, faults
from kfac_pytorch_tpu import health as health_lib
from kfac_pytorch_tpu.plan import (build_cohorts, build_decomp_shard,
                                   build_plan, default_bucket_fn)

#: decomposition-implementation knob values (the autotuner's ladder
#: restates this tuple in autotune.DECOMP_IMPLS — it must stay
#: stdlib-importable; cross-module agreement is pinned by test).
#: 'xla' = the cold kernel (QDWH eigh / batched Cholesky); 'subspace' /
#: 'jacobi' = warm eigh kernels (eigh variants only); 'newton_schulz' =
#: the warm GEMM inverse (cholesky variants only); 'auto' resolves per
#: method to the MXU-shaped warm kernel.
DECOMP_IMPLS = ('xla', 'auto', 'jacobi', 'subspace', 'newton_schulz')

#: capture-kernel ladder (ISSUE 19; autotune.CAPTURE_IMPLS restates
#: this tuple — cross-module agreement is pinned by test). 'xla' = the
#: reference ops/factors.py path; 'pallas' = the fused capture kernels
#: (ops/pallas_capture.py: patch-extract + factor GEMM + EMA epilogue,
#: interpreter mode off-TPU); 'auto' resolves to 'pallas'. None keeps
#: the legacy path untouched AND hides the rung from the tuner.
CAPTURE_IMPLS = ('xla', 'pallas', 'auto')

#: impls that warm-start from the stored decomposition — an explicit
#: iterative ``decomp_impl`` implies warm seeding without requiring
#: ``warm_start_basis`` (the tuner flips the knob mid-run; the seeds
#: are what make the iterative rung cheap).
_WARM_IMPLS = ('auto', 'jacobi', 'subspace', 'newton_schulz')


class KFACState(flax.struct.PyTreeNode):
    """Factor + decomposition state, stacked-bucket layout (plan.py).

    ``factors``/decomposition arrays are globally shaped ``[rows, D, D]``;
    under a mesh the factor rows are sharded over the kfac axis (see
    ``KFAC.state_pspecs``). The reference equivalents are the per-module
    dicts m_A/m_G/m_inv_A/m_inv_G/m_QA/m_dA/...
    (kfac_preconditioner_base.py:107-110).

    ``comm_err`` is the error-feedback residual of the lossy factor-stats
    reduce (``comm_precision`` in {'bf16','int8'} on an MPD variant):
    per device, the quantization error of its LAST compressed stats
    contribution, keyed like the stats stack and re-entered into the
    next reduce (collectives.pmean_scatter_ef). None when no lossy reduce
    exists (fp32, DP variants) — defaulted so pre-compression
    constructions and checkpoints keep working unchanged. Like the
    E-KFAC scales it is transport-transient: ``reshard_kfac_state``
    zero-fills it on an elastic world change and it re-accumulates.
    """
    step: jnp.ndarray
    factors: Dict[str, jnp.ndarray]
    decomp: Dict[str, Dict[str, jnp.ndarray]]
    comm_err: Optional[Dict[str, jnp.ndarray]] = None


@flax.struct.dataclass
class KFACHyperParams:
    """Traced hyper-parameters (schedulable without recompile)."""
    lr: jnp.ndarray
    damping: jnp.ndarray


_VARIANTS = {
    'inverse': dict(stats_reduce='pmean', method='cholesky', comm_mode=None),
    'eigen': dict(stats_reduce='pmean', method='eigh', comm_mode='inverse'),
    'inverse_dp': dict(stats_reduce='local', method='cholesky',
                       comm_mode='pred'),
    'eigen_dp': dict(stats_reduce='local', method='eigh', comm_mode='pred'),
    # beyond reference: E-KFAC (George et al. 2018) — the eigen layout
    # plus per-example second moments in the joint eigenbasis replacing
    # the Kronecker eigenvalue product (engine.update_ekfac_scales);
    # 'ekfac_dp' applies DP-KFAC's owner-local-statistics semantics to
    # the moments too (engine.update_ekfac_scales_local — zero scale
    # communication, composing with the comm_pred flagship layout)
    'ekfac': dict(stats_reduce='pmean', method='eigh',
                  comm_mode='inverse', ekfac=True),
    'ekfac_dp': dict(stats_reduce='local', method='eigh',
                     comm_mode='pred', ekfac=True),
}


_EKFAC_DAMPING_WARNED = False


def _warn_ekfac_damping_once(damping):
    """One-time heads-up that ekfac variants want their own damping.

    The exact second-moment denominators are systematically >= the
    Kronecker eigenvalue product (the eigen variants' denominators), so
    a lambda tuned for 'eigen'/'eigen_dp' can under-damp ekfac — on the
    NOTES r4 MLP ladder the preferred lambda was 10x the eigen recipe's,
    while on conv the shared value worked. Fires once per process
    (VERDICT r4 #4); silence with ``warnings.filterwarnings``.
    """
    global _EKFAC_DAMPING_WARNED
    if _EKFAC_DAMPING_WARNED:
        return
    _EKFAC_DAMPING_WARNED = True
    import warnings
    warnings.warn(
        f'ekfac variants replace the Kronecker eigenvalue product with '
        f'exact (typically larger) second moments in the denominator — '
        f'a damping calibrated for an eigen variant (got {damping}) may '
        'be too small here. If this config was tuned on eigen/eigen_dp, '
        'sweep damping upward (3x/10x) before judging ekfac; see the '
        'KFAC docstring damping note and the NOTES r4 ladder.',
        stacklevel=3)


class KFAC:
    """Distributed K-FAC gradient preconditioner.

    Args mirror the reference constructor (kfac_preconditioner_base.py:66-99)
    plus the mesh placement knobs:

      variant: one of 'inverse' | 'eigen' | 'inverse_dp' | 'eigen_dp'
        (reference parity) or 'ekfac' | 'ekfac_dp' (beyond reference).
        DAMPING NOTE for the ekfac variants: their denominators are
        exact per-example second moments in the joint eigenbasis, which
        are systematically >= the Kronecker eigenvalue product they
        replace (Cauchy-Schwarz on the cross terms) — so a ``damping``
        calibrated for an eigen variant can be too SMALL relative to
        the ekfac spectrum. On an MLP task the preferred lambda was 10x
        the eigen recipe's (NOTES r4 damping ladder: .832 at 0.3 vs
        .678 at 0.03); on conv the shared recipe value worked. When
        switching a tuned eigen config to ekfac, sweep damping upward
        (3x/10x) before judging the variant; a one-time warning points
        here (pinned by tests/test_warm_accuracy_gate.py's ladder).
      lr, damping, fac_update_freq, kfac_update_freq, kl_clip,
      factor_decay, exclude_vocabulary_size, hook_enabled, exclude_parts:
        reference semantics.
      communicate_inverse_or_not: 'inverse' variant only — communicate
        inverse KFs instead of preconditioned grads (inv.py:41).
      num_devices / axis_name: size of the kfac mesh axis and its name
        inside shard_map; axis_name=None is the world=1 zero-comm path.
      mesh_axes: composed-mesh spec ('dp2xtp2', 'dp4xep2', a parsed
        ``meshplan.AxisSpec`` tuple) — the axis-aware lane (README
        "K-FAC on composed meshes"). The K-FAC world derives from its
        data/sequence axes (so num_devices/axis_name must be left
        unset), the factor plan stays the plain data-world plan, and
        tensor-replicated factor rows (column-A / row-G per
        ``mesh_rules``) gain a pmean over the tensor axis; expert- and
        pipeline-axis factors are owner-local — zero factor bytes on
        those axes. Live moves go through ``replan(mesh_axes=...)``.
      mesh_rules: per-layer ``meshplan.LayerAxisRule`` tuple (default:
        the stock parallel/ families — ``tp.axis_rules()`` names; use
        ``tp.axis_rules(column=..., row=...)`` / ``moe.axis_rules``
        for custom layer names). Requires mesh_axes.
      assignment: 'round_robin' (reference) | 'balanced' (LPT scheduler).
      distribute_layer_factors: eigen variant — put A and G of one layer on
        different devices when the mesh outnumbers layers (eigen.py:66-71);
        default auto.
      basis_update_freq: eigh variants only (beyond reference) — full
        eigendecomposition every this-many steps; intermediate
        ``kfac_update_freq`` hits re-fit only the eigenvalues in the
        retained eigenbasis (E-KFAC-style amortization, two matmuls per
        bucket instead of an eigh). None (default) = every inverse update
        is a full decomposition, the reference cadence.
      warm_start_basis: beyond reference — decompositions after the
        first start from the previous one. Eigh variants: the stored
        eigenbasis seeds perturbative tracking (ops.subspace_eigh,
        KFAC_EIGH_IMPL='subspace'/'auto' — the MXU-shaped warm kernel,
        chosen by real-chip measurement) or rotated Jacobi sweeps
        ('jacobi'); composes with basis_update_freq. Cholesky variants:
        the stored inverse seeds Newton-Schulz iteration
        (ops.newton_schulz_inverse) with a residual-gated Cholesky
        fallback — pure matmuls on the inverse-update hot path.
      warm_sweeps: iteration count for warm-started full decompositions:
        Jacobi sweeps (None = the kernel's warm default, 5), subspace
        tracking steps (None = 2), or Newton-Schulz iterations
        (None = 2). The defaults are calibrated for the
        stat_decay=0.95 / <=10-step full-interval drift regime — raise
        for longer intervals between fulls (large basis_update_freq /
        kfac_update_freq) or faster factor decay: the stored
        decomposition drifts further between fulls and the default can
        under-converge (Newton-Schulz self-reports: a stale seed fails
        the residual gate and falls back to Cholesky).
      cold_restart_every: with warm_start_basis, force a cold (from
        scratch) full decomposition after this many consecutive warm
        ones — the chained basis Q <- Q @ V' accumulates ~1e-7
        orthogonality error per warm full, and the periodic cold full
        resets it. Must be a positive int.
      stagger: staggered inverse refresh (beyond reference — the KAISA /
        Osawa et al. amortization done evenly): instead of decomposing
        EVERY factor on ``kfac_update_freq``-boundary steps (a periodic
        multi-x step-time spike), the device-major rows are partitioned
        into ``kfac_update_freq`` cost-balanced cohorts
        (plan.build_cohorts, eigh cost ~ D^3) and every step decomposes
        only cohort ``step % kfac_update_freq`` — the same per-slot
        staleness contract (each slot refreshed once per window), cost
        spread evenly so the second-order work hides behind the
        first-order step. The cohort index is a TRACED scalar, so the
        trainer's compiled-variant count does not grow with the freq.
        Double-buffered publish: the step preconditions with the
        PREVIOUS stored table while the freshly decomposed cohort rows
        are merged (and, in comm_mode='inverse', all-gathered at
        ~1/kfac_update_freq of the full volume, overlappable with the
        pred einsums) into the state for the NEXT step — one extra step
        of staleness for the refreshed cohort, well inside the contract
        ``kfac_update_freq`` already accepts. Mutually exclusive with
        the basis_update_freq / warm_start_basis amortizations and the
        ekfac variants (those re-use the full-refresh structure).
        The first decomposition of a run is always a full one (the
        trainer's cold-start gate); staggering begins after it.
      comm_precision: wire dtype of the factor collectives (beyond
        reference — EF-SGD lineage, Seide et al. 2014 / Karimireddy et
        al. 2019): 'fp32' (default, bit-identical to the uncompressed
        path), 'bf16' (2x byte reduction on every factor collective), or
        'int8' (4x on the gather collectives via per-row absmax scales;
        the stats REDUCE floors at bf16 — an XLA all-reduce cannot
        integer-accumulate without overflow). Lossy modes compensate the
        stats reduce with an error-feedback residual carried in
        ``KFACState.comm_err`` (folds into the factor EMAs — every
        device's time-averaged contribution stays unbiased); the gathers
        quantize per owner (one contributor per row — no accumulation
        error). The gradient allreduce is NEVER compressed: the SGD
        floor is untouched. ``axis_name=None`` stays a zero-comm,
        zero-compression identity path.
      comm_prefetch: comm_mode='inverse' only (beyond reference) —
        extend PR 4's double-buffer to the FULL refresh: on an
        inverse-update step the freshly gathered decomposition is
        published for the NEXT step while THIS step preconditions with
        the previous table, so the CommunicateInverse gather has no
        same-step consumer and XLA can overlap it with the pred einsums
        (one step of decomposition staleness, well inside the
        ``kfac_update_freq`` contract — the same trade ``stagger``
        already makes per cohort). The trainer keeps the first
        decomposition of a run un-prefetched (a cold state would
        precondition with zeros). Redundant (but harmless) with
        ``stagger``, which is always double-buffered.
      decomp_impl: the decomposition implementation, promoted to a
        first-class runtime knob (beyond reference — autotune.KNOB_ATTRS
        rung; README "Attacking the decomposition wall"): 'xla' (the
        cold kernel — QDWH eigh / batched Cholesky), 'subspace' or
        'jacobi' (warm eigh kernels, eigh variants only),
        'newton_schulz' (the warm GEMM inverse, Cholesky variants
        only), or 'auto' (the MXU-shaped warm kernel for the method).
        An EXPLICIT iterative value implies warm seeding from the
        stored decomposition — no separate ``warm_start_basis`` needed
        (the per-row NS acceptance gate / subspace degeneracy handling
        keep accuracy safe; see ops/linalg.py). None (default)
        preserves the legacy KFAC_EIGH_IMPL env contract exactly. The
        KnobController ladders this attribute through the arbiter; a
        change retraces the step (the arbiter fires the variant-cache
        invalidators, like comm_precision).
      decomp_shard: mesh-sharded decomposition (beyond reference — the
        tentpole of ROADMAP item 5): the active refresh cohort's rows
        are repartitioned cost-balanced (D³ model) across ALL devices
        instead of decomposed owner-local, shrinking the per-step
        decomposition critical path from ``Σ_b R_b·D³`` to
        ``Σ_b S_b·D³ ≈ 1/P`` of the cohort total — the most-loaded
        owner's cohort stops serializing its idle peers. Costs two
        bounded ``DecompComm`` gathers per step (damped cohort factors
        out, results back), priced in closed form by
        ``FactorPlan.comm_volume(decomp_shard=...)`` and pinned
        byte-for-byte against the compiled HLO by
        scripts/comm_count.py. Implies ``stagger=True`` (the cohort
        tables ARE the work description) and therefore inherits
        stagger's exclusions; incompatible with the
        CommunicateInverse ablation. ``axis_name=None`` degenerates to
        the owner-local path bit-exactly.
      health: the numerical-health guard (beyond reference, health.py).
        True (default) enables the in-engine screens with the default
        ladder: factor-EMA rows and decomposition rows that come back
        non-finite fall back to the last good value (identity when
        cold), so one blown eigh/Cholesky can never poison the state.
        Pass a ``health.HealthConfig`` to tune the damping-escalation
        ladder the trainer drives (escalate_after / damping_factor /
        max_rungs / recover_after), or False to disable every screen —
        the guards are pure pass-through selects when the inputs are
        finite, so disabling only buys back their (tiny) compile cost.
    """

    def __init__(self, variant='eigen_dp', lr=0.1, damping=0.001,
                 fac_update_freq=1, kfac_update_freq=1,
                 communicate_inverse_or_not=False, kl_clip=0.001,
                 factor_decay=0.95, exclude_vocabulary_size=None,
                 hook_enabled=True, exclude_parts='', batch_averaged=True,
                 num_devices=1, axis_name=None, assignment='round_robin',
                 distribute_layer_factors=None, bucket_fn=None, eps=1e-10,
                 basis_update_freq=None, warm_start_basis=False,
                 warm_sweeps=None, cold_restart_every=50, stagger=False,
                 health=True, comm_precision='fp32', comm_prefetch=False,
                 decomp_impl=None, decomp_shard=False, comm_mode=None,
                 capture_impl=None, mesh_axes=None, mesh_rules=None):
        if variant not in _VARIANTS:
            raise KeyError(f'unknown variant {variant!r}')
        cfg = dict(_VARIANTS[variant])
        if cfg['comm_mode'] is None:  # 'inverse' variant honors the flag
            cfg['comm_mode'] = ('inverse' if communicate_inverse_or_not
                                else 'pred')
        if comm_mode is not None:
            # ISSUE 14: comm_mode is a RUNTIME knob now — the variant
            # only picks the starting mode, and an explicit override
            # (the trainers' --kfac-comm-mode, a kfac-serve relaunch
            # carrying an autotune-adopted switch) starts on the other
            # road of the same layout. The live switch is KFAC.replan.
            if comm_mode not in ('inverse', 'pred'):
                raise ValueError("comm_mode must be 'inverse' or 'pred', "
                                 f'got {comm_mode!r}')
            cfg['comm_mode'] = comm_mode
        self.variant = variant
        self.stats_reduce = cfg['stats_reduce']
        self.method = cfg['method']
        self.comm_mode = cfg['comm_mode']
        self.ekfac = cfg.get('ekfac', False)
        if self.ekfac:
            _warn_ekfac_damping_once(damping)
        self.lr = lr
        self.damping = damping
        self.fac_update_freq = fac_update_freq
        self.kfac_update_freq = kfac_update_freq
        self.kl_clip = kl_clip if (kl_clip is not None and kl_clip > 0) \
            else None
        self.factor_decay = factor_decay
        self.exclude_vocabulary_size = exclude_vocabulary_size
        self.hook_enabled = hook_enabled
        self.batch_averaged = batch_averaged
        self.num_devices = num_devices
        self.axis_name = axis_name
        # mesh-plan subsystem: a composed-mesh spec ('dp2xtp2' or parsed
        # AxisSpec tuple) makes the preconditioner axis-aware — the
        # K-FAC world (num_devices/axis_name) derives from the DATA
        # axes, and setup() builds a MeshFactorPlan whose base is the
        # plain data-world plan (the step path reads only that; the one
        # mesh-specific seam is engine.update_factors' extra_reduce)
        self.mesh_axes = None
        self.mesh_rules = mesh_rules
        self._mesh_plan = None
        if mesh_axes is not None:
            from kfac_pytorch_tpu.meshplan import axes as _ma
            _axes = _ma.parse_mesh_spec(mesh_axes)
            world = _ma.world_size(_axes)
            dnames = _ma.data_axis_names(_axes)
            derived = dnames[0] if len(dnames) == 1 else dnames
            if num_devices not in (1, world):
                raise ValueError(
                    f'mesh_axes={_ma.format_mesh_spec(_axes)!r} has a '
                    f'{world}-way data world but num_devices={num_devices} '
                    '— drop num_devices (it derives from the mesh spec)')
            if axis_name is not None and axis_name != derived:
                raise ValueError(
                    f'mesh_axes={_ma.format_mesh_spec(_axes)!r} puts the '
                    f'K-FAC world on {derived!r} but axis_name='
                    f'{axis_name!r} — drop axis_name (it derives from '
                    'the mesh spec)')
            self.mesh_axes = _axes
            self.num_devices = world
            self.axis_name = derived
        elif mesh_rules is not None:
            raise ValueError('mesh_rules without mesh_axes has nothing '
                             'to apply to — pass mesh_axes')
        self.assignment = assignment
        self.distribute_layer_factors = distribute_layer_factors
        self.bucket_fn = bucket_fn or default_bucket_fn
        self.eps = eps
        if basis_update_freq is not None and self.method != 'eigh':
            raise ValueError('basis_update_freq applies to eigh variants')
        self.basis_update_freq = basis_update_freq
        if warm_start_basis and self.method == 'eigh':
            import os
            import warnings
            if os.environ.get('KFAC_EIGH_IMPL', 'xla') == 'xla':
                warnings.warn(
                    'warm_start_basis has no effect on the XLA eigh path '
                    "(QDWH cannot warm-start) — set KFAC_EIGH_IMPL="
                    "'subspace' (or 'auto'/'jacobi') to use it",
                    stacklevel=2)
        self.warm_start_basis = warm_start_basis
        if warm_start_basis and warm_sweeps is None:
            interval = basis_update_freq or kfac_update_freq
            if interval > 10:
                import warnings
                warnings.warn(
                    f'warm_start_basis with a {interval}-step interval '
                    'between full decompositions: the default warm_sweeps '
                    '(5) is calibrated for <=10-step basis drift — pass '
                    'warm_sweeps>=8 if eigen accuracy degrades',
                    stacklevel=2)
        self.warm_sweeps = warm_sweeps
        # every warm full compounds ~1e-7 orthogonality error into the
        # chained basis Q <- Q @ V'; a periodic cold full resets it.
        # The default (50) keeps the accumulated error ~5e-6 — far below
        # the f32 decomposition noise floor
        if not (isinstance(cold_restart_every, int)
                and cold_restart_every > 0):
            raise ValueError('cold_restart_every must be a positive int '
                             f'(got {cold_restart_every!r})')
        self.cold_restart_every = cold_restart_every
        # decomposition-implementation knob (tentpole b): an EXPLICIT
        # value routes through the traced programs (ops.sym_eig impl /
        # the NS warm inverse) and joins the autotuner's KNOB_ATTRS
        # ladder; None preserves the legacy KFAC_EIGH_IMPL env path
        # exactly (env read at trace time, warm only with
        # warm_start_basis) so existing configs are untouched
        if decomp_impl is not None:
            if decomp_impl not in DECOMP_IMPLS:
                raise ValueError(
                    f'decomp_impl must be one of {DECOMP_IMPLS}, '
                    f'got {decomp_impl!r}')
            if (decomp_impl in ('subspace', 'jacobi')
                    and self.method != 'eigh'):
                raise ValueError(
                    f'decomp_impl={decomp_impl!r} is an eigh kernel; '
                    f'variant {variant!r} decomposes by Cholesky — use '
                    "'newton_schulz' (or 'auto') there")
            if decomp_impl == 'newton_schulz' and self.method != 'cholesky':
                raise ValueError(
                    "decomp_impl='newton_schulz' replaces the Cholesky "
                    f'inverse; variant {variant!r} eigendecomposes — '
                    "use 'subspace' (or 'auto') there")
        self.decomp_impl = decomp_impl
        # capture-implementation knob (ISSUE 19): an EXPLICIT value
        # routes factor capture through ops/pallas_capture.py (fused
        # patch-extract + statistic GEMMs + EMA/wire epilogues) and
        # joins the autotuner's KNOB_ATTRS ladder; None preserves the
        # ops/factors.py path exactly, so existing configs are untouched
        if capture_impl is not None and capture_impl not in CAPTURE_IMPLS:
            raise ValueError(
                f'capture_impl must be one of {CAPTURE_IMPLS}, '
                f'got {capture_impl!r}')
        self.capture_impl = capture_impl
        self.decomp_shard = bool(decomp_shard)
        if self.decomp_shard and not stagger:
            # sharding repartitions the ACTIVE COHORT's rows — it is a
            # stagger-family feature, so the flag implies the staggered
            # schedule (and inherits its exclusions below)
            stagger = True
        self.stagger = bool(stagger)
        if self.decomp_shard and 'CommunicateInverse' in exclude_parts:
            raise ValueError(
                'decomp_shard IS a communication pattern — the '
                'CommunicateInverse ablation cannot exclude the shard '
                'exchange (drop decomp_shard for that ablation)')
        if self.stagger:
            if self.ekfac:
                raise ValueError(
                    'stagger is not supported for the ekfac variants: the '
                    'per-example moment rotation assumes a whole-table '
                    'basis change, not a per-cohort one')
            if basis_update_freq is not None or warm_start_basis:
                raise ValueError(
                    'stagger is an alternative amortization of the inverse '
                    'refresh — it does not compose with basis_update_freq '
                    'or warm_start_basis (pick one; see README '
                    '"Staggered refresh")')
        self._cohorts = None
        self._shard_plan = None
        # per-bucket stagger cadence overrides ({bucket dim: stretch},
        # plan.build_cohorts bucket_freq) — set via replan(); empty =
        # the uniform cadence
        self.bucket_stagger_freq = {}
        # resolved factor distribution (setup records it; replan keeps
        # it except where comm_pred forbids the factor-wise split)
        self._distributed = None
        # a queued replan spec (request_replan): the trainer applies it
        # host-side between steps (apply_pending_replan) — the
        # double-buffered swap point where no traced program is running
        self._pending_replan = None
        from kfac_pytorch_tpu.parallel import collectives as _coll
        self.comm_precision = _coll.check_wire_dtype(comm_precision)
        self.comm_prefetch = bool(comm_prefetch)
        if self.comm_prefetch:
            if self.comm_mode != 'inverse':
                raise ValueError(
                    "comm_prefetch applies to comm_mode='inverse' (the "
                    'decomposition gathers); the comm_pred variants '
                    'gather preconditioned gradients, which ARE the '
                    "step's consumer and cannot be deferred")
            if self.ekfac:
                raise ValueError(
                    'comm_prefetch is not supported for the ekfac '
                    'variants: the scale moments must be estimated in '
                    'the same basis the pred consumes, which prefetch '
                    'splits across steps')
        self.health = health_lib.resolve(health)
        # deterministic fault injection (chaos tests): the env snapshot
        # happens here, at construction, so the traced step is static
        self._faults = faults.from_env()
        # exclude_parts ablation flags (kfac_preconditioner_base.py:96-99)
        self.exclude_communicate_inverse = 'CommunicateInverse' in exclude_parts
        self.exclude_compute_inverse = 'ComputeInverse' in exclude_parts
        self.exclude_communicate_factor = 'CommunicateFactor' in exclude_parts
        self.exclude_compute_factor = 'ComputeFactor' in exclude_parts
        self.plan = None
        # the single writer of the runtime knobs (fac/kfac_update_freq,
        # damping, comm_precision): lazily created by
        # autotune.arbiter_for — KFACParamScheduler, the straggler
        # governor and the online tuner all PROPOSE to it instead of
        # assigning these attributes (tests/test_autotune.py pins that
        # nothing else writes them)
        self._knob_arbiter = None

    # -- setup ------------------------------------------------------------

    def setup(self, metas):
        """Build the static factor plan from capture layer metadata.

        ≙ _register_module_hooks + schedule_module_ranks (reference:
        kfac_preconditioner_base.py:132-149, inv.py:62-77). The vocab-size
        exclusion is applied here if not already filtered.

        ``metas`` is the ``{path: LayerMeta}`` dict from
        ``capture.collect_layer_meta``, or a plain meta list — e.g.
        another plan's ``.metas``, which is how the elastic resume path
        (``resilience.elastic_resume``) rebuilds the OLD world's plan
        over the layer list the new world's plan discovered.
        """
        if not isinstance(metas, dict):
            metas = {m.path: m for m in metas}
        if self.exclude_vocabulary_size is not None:
            from kfac_pytorch_tpu.capture import filter_vocab_head
            metas = filter_vocab_head(metas, self.exclude_vocabulary_size)
        distribute = self.distribute_layer_factors
        if self.variant in ('eigen', 'ekfac') and distribute is None:
            # reference auto rule: factor-wise split iff world > #layers
            # (eigen.py:66-71) — but comm_pred forbids the factor-wise
            # split (rank_a == rank_g), so a comm_mode='pred' override
            # (ctor or replan) collapses the auto rule to whole-layer
            # ownership, mirroring replan()'s resolution: any config
            # the live switch can land on must be constructible cold
            # (the adopted-knobs relaunch restarts trainers there)
            distribute = (self.comm_mode != 'pred'
                          and self.num_devices > len(metas))
        if self.mesh_axes is not None:
            from kfac_pytorch_tpu.meshplan.plan import build_mesh_plan
            self._mesh_plan = build_mesh_plan(
                metas, self.mesh_axes, comm_mode=self.comm_mode,
                assignment=self.assignment,
                distribute_layer_factors=bool(distribute),
                bucket_fn=self.bucket_fn, rules=self.mesh_rules)
            # the step path reads the plain data-world base plan — the
            # mesh layer only adds the extra_reduce tables at step time
            self.plan = self._mesh_plan.base
        else:
            self._mesh_plan = None
            self.plan = build_plan(
                metas, num_devices=self.num_devices,
                comm_mode=self.comm_mode, assignment=self.assignment,
                distribute_layer_factors=bool(distribute),
                bucket_fn=self.bucket_fn)
        self._distributed = bool(distribute)
        self._cohorts = None
        if self.stagger:
            self.rebase_cohorts()
        return self.plan

    def rebase_cohorts(self):
        """(Re)build the staggered cohort layout for the CURRENT
        ``kfac_update_freq``. Called by :meth:`setup`, by
        KFACParamScheduler after a frequency rescale, and lazily by the
        trainer on every staggered dispatch (which also covers the
        StragglerGovernor's temporary frequency stretches). No-op when
        the layout already matches; returns the layout (None when
        stagger is off or setup hasn't run)."""
        if not self.stagger or self.plan is None:
            return None
        f = max(1, int(self.kfac_update_freq))
        overrides = {int(k): max(1, int(v))
                     for k, v in (self.bucket_stagger_freq or {}).items()}
        if (self._cohorts is None or self._cohorts.base_freq != f
                or self._cohorts.bucket_freq != overrides):
            self._cohorts = build_cohorts(self.plan, f,
                                          bucket_freq=overrides)
            self._shard_plan = None
        if self.decomp_shard and self._shard_plan is None:
            self._shard_plan = build_decomp_shard(self.plan, self._cohorts)
        return self._cohorts

    @property
    def cohorts(self):
        """The current staggered cohort layout (plan.CohortPlan)."""
        return self._cohorts

    @property
    def decomp_shard_plan(self):
        """The mesh-sharded decomposition layout
        (plan.DecompShardPlan), or None when ``decomp_shard`` is off."""
        return self._shard_plan

    @property
    def mesh_plan(self):
        """The axis-aware :class:`~kfac_pytorch_tpu.meshplan.plan.
        MeshFactorPlan` (or None without ``mesh_axes``). Its ``base``
        IS ``self.plan``; the per-axis comm ledger is
        ``mesh_plan.comm_volume(...)``."""
        return self._mesh_plan

    # -- live replanning (ISSUE 14) ---------------------------------------

    @property
    def pending_replan(self):
        """The queued :meth:`request_replan` spec (or None). The trainer
        checks this at the top of every host step and applies it via
        :meth:`apply_pending_replan` — the atomic between-steps swap."""
        return self._pending_replan

    def request_replan(self, _invalidate=True, **spec):
        """Queue a replan to be applied at the next step boundary.

        The knob arbiter calls this when the tuner commits a
        ``comm_mode`` switch (it cannot apply the switch itself — the
        factor state lives in the trainer's TrainState, and the swap
        must happen between steps, never under a traced program).
        Later requests merge per key; ``_invalidate=False`` records
        that the caller already fired the variant-cache invalidators
        (the arbiter fires them exactly once at commit time). The flag
        ORs across merged requests: one caller that still needs the
        invalidation keeps it armed even when an arbiter request (which
        already fired) merges in after it."""
        pend = dict(self._pending_replan or {'_invalidate': False})
        invalidate = bool(pend.get('_invalidate', False)) or _invalidate
        pend.update(spec)
        pend['_invalidate'] = invalidate
        self._pending_replan = pend
        return pend

    def apply_pending_replan(self, kfac_state):
        """Apply (and clear) the queued replan against ``kfac_state``;
        returns the (possibly verbatim) transported state. No-op when
        nothing is pending."""
        spec = self._pending_replan
        self._pending_replan = None
        if not spec:
            return kfac_state
        return self.replan(kfac_state, **spec)

    def replan(self, kfac_state=None, *, comm_mode=None, num_devices=None,
               bucket_overrides=None, variant=None,
               axis_name='__unchanged__', mesh_axes='__unchanged__',
               _invalidate=True):
        """Rebuild the :class:`~kfac_pytorch_tpu.plan.FactorPlan` (and
        the staggered cohort/shard tables) MID-RUN and transport the
        factor state into the new layout — the primitive behind applied
        comm-mode switching, per-bucket cadence tuning and
        zero-relaunch elasticity (ROADMAP item 2).

        Args (every one optional — unset keeps the current value):
          kfac_state: the live :class:`KFACState` to transport; None
            rebuilds the plan only (no state exists yet). Host-side:
            call OUTSIDE jit with the state addressable. When the row
            layout is unchanged (a pure comm-mode switch) the state is
            carried VERBATIM — not a byte moves, only the traced
            programs change.
          comm_mode: 'inverse' | 'pred' — the applied switch between
            communicating decompositions and communicating
            preconditioned gradients. Factor EMAs, decompositions and
            the EF residual all carry exactly (same rows, same
            owners); E-KFAC scale moments are comm-mode shaped and
            re-accumulate (their existing transport contract).
          num_devices: the new world size — the elastic lane.
            Factors AND (same-method) decompositions transport through
            ``reshard_kfac_state``'s per-layer row remap, so the
            resumed world preconditions immediately instead of passing
            gradients through until the next refresh.
          bucket_overrides: per-bucket stagger cadence
            ``{bucket dim: stretch}`` (``plan.build_cohorts``
            bucket_freq; ``{}`` clears). Stagger configs only.
          variant: switch the variant family (e.g. 'eigen' <->
            'inverse_dp'): stats_reduce/method/comm_mode re-derive from
            the variant table (an explicit ``comm_mode=`` still wins).
            Cross-METHOD switches rebuild the decomposition from the
            carried factors at the next inverse update (the trainer's
            seen-inverse gate re-arms through the invalidator).
          axis_name: the mesh axis of the new plan (elastic 1<->N
            moves); default keeps the current one.
          mesh_axes: a composed-mesh spec ('dp2xtp2' / AxisSpec tuple /
            None to clear) — the axis-aware lane. The K-FAC world
            (num_devices + axis_name) derives from its data axes, so
            it is mutually exclusive with passing those directly. A
            move that keeps the data world (dp2xtp2 -> dp2) keeps the
            base row layout — the factor state carries VERBATIM, only
            the extra tensor-axis reduce enters/leaves the trace.

        The swap is atomic at the host level: the new plan, tables and
        transported state are fully built BEFORE any attribute of this
        preconditioner changes, so a failed replan leaves the run
        untouched. The KnobArbiter invalidators fire exactly once per
        replan (``_invalidate=False`` when the arbiter already fired
        them at commit time), so every attached trainer retraces
        against the new plan and nothing else recompiles.
        """
        import copy
        import logging
        assert self.plan is not None, 'call setup() first'
        from kfac_pytorch_tpu.plan import same_row_layout
        old_plan = self.plan
        log = logging.getLogger(__name__)

        # -- resolve the target configuration -----------------------------
        new_variant = self.variant if variant is None else variant
        if new_variant not in _VARIANTS:
            raise KeyError(f'unknown variant {new_variant!r}')
        cfg = dict(_VARIANTS[new_variant])
        if variant is None:
            new_mode = self.comm_mode
        else:
            new_mode = cfg['comm_mode'] or 'pred'
        if comm_mode is not None:
            if comm_mode not in ('inverse', 'pred'):
                raise ValueError("comm_mode must be 'inverse' or 'pred', "
                                 f'got {comm_mode!r}')
            new_mode = comm_mode
        new_method = cfg['method'] if variant is not None else self.method
        new_reduce = (cfg['stats_reduce'] if variant is not None
                      else self.stats_reduce)
        new_ekfac = (cfg.get('ekfac', False) if variant is not None
                     else self.ekfac)
        new_P = self.num_devices if num_devices is None else int(num_devices)
        if new_P < 1:
            raise ValueError(f'num_devices must be >= 1, got {new_P}')
        new_axis = (self.axis_name if axis_name == '__unchanged__'
                    else axis_name)
        if mesh_axes == '__unchanged__':
            new_mesh = self.mesh_axes
            if (new_mesh is not None
                    and (num_devices is not None
                         or axis_name != '__unchanged__')):
                raise ValueError(
                    'this preconditioner is mesh-planned — resize its '
                    "K-FAC world through mesh_axes ('dp4xtp2', ...), "
                    'not num_devices/axis_name, so the axis tables '
                    'move with it')
        else:
            if num_devices is not None or axis_name != '__unchanged__':
                raise ValueError(
                    'mesh_axes derives num_devices and axis_name from '
                    'its data axes — do not also pass them')
            if mesh_axes is None:
                new_mesh = None  # clear: plain plan over current world
            else:
                from kfac_pytorch_tpu.meshplan import axes as _ma
                new_mesh = _ma.parse_mesh_spec(mesh_axes)
                new_P = _ma.world_size(new_mesh)
                dnames = _ma.data_axis_names(new_mesh)
                new_axis = dnames[0] if len(dnames) == 1 else dnames
        mesh_changed = new_mesh != self.mesh_axes
        if bucket_overrides is None:
            new_overrides = dict(self.bucket_stagger_freq or {})
        else:
            if not self.stagger:
                raise ValueError(
                    'bucket_overrides tune the STAGGERED cohort cadence '
                    '(KFAC(stagger=True)); this preconditioner refreshes '
                    'whole tables')
            new_overrides = {int(k): int(v)
                             for k, v in dict(bucket_overrides).items()}
            if any(v < 1 for v in new_overrides.values()):
                raise ValueError('bucket_overrides stretches must be '
                                 f'>= 1, got {new_overrides}')
            if any(v & (v - 1) or v > 64 for v in new_overrides.values()):
                # power-of-two stretches keep the cohort-table window at
                # F * max(stretch); coprime stretches would lcm-explode
                # the static tables (231x for {3,7,11}) that get baked
                # into every traced program
                raise ValueError('bucket_overrides stretches must be '
                                 'powers of two <= 64, got '
                                 f'{new_overrides}')
            unknown = sorted(set(new_overrides)
                             - set(old_plan.bucket_dims))
            if unknown:
                # validated HERE, before the atomic commit — a bad dim
                # failing later inside rebase_cohorts would leave the
                # preconditioner half-swapped and wedge every
                # subsequent staggered dispatch
                raise ValueError(
                    f'bucket_overrides names unknown bucket dims '
                    f'{unknown} (plan has {old_plan.bucket_dims})')

        # -- validate the combination (the ctor rules, re-checked) --------
        if new_mode == 'pred' and self.comm_prefetch:
            raise ValueError(
                "cannot replan to comm_mode='pred' with comm_prefetch: "
                'the pred gather IS the step consumer and cannot be '
                'deferred (drop comm_prefetch first)')
        if new_ekfac and self.stagger:
            raise ValueError('cannot replan a staggered preconditioner '
                             'onto an ekfac variant (stagger exclusion)')
        if self.decomp_impl is not None:
            if (self.decomp_impl in ('subspace', 'jacobi')
                    and new_method != 'eigh'):
                raise ValueError(
                    f'decomp_impl={self.decomp_impl!r} is an eigh kernel '
                    f'but the replan target decomposes by {new_method} — '
                    'switch decomp_impl first')
            if (self.decomp_impl == 'newton_schulz'
                    and new_method != 'cholesky'):
                raise ValueError(
                    "decomp_impl='newton_schulz' replaces the Cholesky "
                    f'inverse but the replan target uses {new_method} — '
                    'switch decomp_impl first')
        # comm_pred forbids the factor-wise split (reference asserts
        # rank_a == rank_g there): a distributed eigen layout replans to
        # pred by collapsing back to whole-layer ownership. The
        # resolution MIRRORS setup() exactly for the target config —
        # the ctor's explicit flag, else the eigen/ekfac auto rule
        # re-resolved for the new world/variant (a non-eigen target
        # never auto-distributes) — because a replanned plan must be
        # the plan a fresh setup of that config would build, or the
        # adopted-knobs relaunch would land state on a different row
        # layout than the live-switched incarnation ran.
        distribute = self.distribute_layer_factors
        if distribute is None and new_variant in ('eigen', 'ekfac'):
            distribute = (new_mode != 'pred'
                          and new_P > len(old_plan.metas))
        distribute = bool(distribute)
        if new_mode == 'pred':
            distribute = False

        # -- build the new layout + transported state FIRST ---------------
        new_mesh_plan = None
        if new_mesh is not None:
            from kfac_pytorch_tpu.meshplan.plan import build_mesh_plan
            new_mesh_plan = build_mesh_plan(
                {m.path: m for m in old_plan.metas}, new_mesh,
                comm_mode=new_mode, assignment=self.assignment,
                distribute_layer_factors=distribute,
                bucket_fn=self.bucket_fn, rules=self.mesh_rules)
            new_plan = new_mesh_plan.base
        else:
            new_plan = build_plan(
                {m.path: m for m in old_plan.metas}, num_devices=new_P,
                comm_mode=new_mode, assignment=self.assignment,
                distribute_layer_factors=distribute,
                bucket_fn=self.bucket_fn)
        clone = copy.copy(self)
        clone.variant = new_variant
        clone.stats_reduce = new_reduce
        clone.method = new_method
        clone.comm_mode = new_mode
        clone.ekfac = new_ekfac
        clone.num_devices = new_P
        clone.axis_name = new_axis
        clone.plan = new_plan
        clone.mesh_axes = new_mesh
        clone._mesh_plan = new_mesh_plan
        clone._distributed = distribute
        clone.bucket_stagger_freq = new_overrides
        clone._cohorts = None
        clone._shard_plan = None

        same_layout = same_row_layout(old_plan, new_plan)
        new_state = kfac_state
        verbatim = False
        if kfac_state is not None:
            verbatim = (
                same_layout and self.method == clone.method
                # scales are comm-mode shaped; the EF residual only
                # exists on lossy MPD reduces — both must agree for a
                # byte-for-byte carry
                and (not (self.ekfac or clone.ekfac)
                     or (self.ekfac == clone.ekfac
                         and self.comm_mode == clone.comm_mode))
                and self._tracks_comm_err == clone._tracks_comm_err
                and ((kfac_state.comm_err is None)
                     == (not clone._tracks_comm_err)))
            if not verbatim:
                from kfac_pytorch_tpu.utils.checkpoint import \
                    reshard_kfac_state
                new_state = reshard_kfac_state(self, clone, kfac_state,
                                               carry_decomp=True)

        # -- commit: swap every table/attr atomically between steps -------
        trace_changed = (
            not same_layout or new_mode != self.comm_mode
            or new_method != self.method or new_reduce != self.stats_reduce
            or new_ekfac != self.ekfac or new_axis != self.axis_name
            or mesh_changed
            or new_overrides != (self.bucket_stagger_freq or {}))
        try:
            from kfac_pytorch_tpu.autotune import _applying
        except ImportError:  # pragma: no cover — autotune is stdlib
            import contextlib
            _applying = contextlib.nullcontext
        with _applying():
            # comm_mode is a KNOB_ATTRS member: the write happens under
            # the arbiter's applying guard (single-writer discipline),
            # and the arbiter re-bases below so it never reads this as
            # a foreign write to adopt
            self.comm_mode = new_mode
        self.variant = new_variant
        self.stats_reduce = new_reduce
        self.method = new_method
        self.ekfac = new_ekfac
        self.num_devices = new_P
        self.axis_name = new_axis
        self.plan = new_plan
        self.mesh_axes = new_mesh
        self._mesh_plan = new_mesh_plan
        self._distributed = distribute
        self.bucket_stagger_freq = new_overrides
        self._cohorts = None
        self._shard_plan = None
        if self.stagger:
            self.rebase_cohorts()
        arb = self._knob_arbiter
        if arb is not None:
            arb.sync_knobs(comm_mode=new_mode)
        log.info(
            'kfac: replan applied comm_mode=%s world=%d%s%s '
            '(layout %s, state %s)', new_mode, new_P,
            f' variant={new_variant}' if variant is not None else '',
            f' bucket_overrides={new_overrides}' if new_overrides else '',
            'unchanged' if same_layout else 'rebuilt',
            'carried verbatim' if verbatim else
            ('transported' if kfac_state is not None else 'none'))
        if _invalidate and trace_changed and arb is not None:
            arb.invalidate()
        elif _invalidate and trace_changed:
            # no arbiter yet -> no trainer registered an invalidator;
            # create it lazily so later trainers still attach to one
            from kfac_pytorch_tpu.autotune import arbiter_for
            arbiter_for(self).invalidate()
        return new_state

    @property
    def resolved_decomp_impl(self):
        """The kernel the traced step actually selects: 'auto' resolves
        per method (subspace for eigh, Newton-Schulz for Cholesky);
        None stays None — engine falls back to the legacy
        KFAC_EIGH_IMPL env read."""
        impl = self.decomp_impl
        if impl == 'auto':
            return 'subspace' if self.method == 'eigh' else 'newton_schulz'
        return impl

    @property
    def resolved_capture_impl(self):
        """The capture path the traced step actually selects: 'auto'
        resolves to the fused Pallas kernels; None stays None — engine
        keeps the ops/factors.py reference path."""
        impl = self.capture_impl
        if impl == 'auto':
            return 'pallas'
        return impl

    @property
    def warm_impl(self):
        """Does the EXPLICIT decomp_impl warm-start from the stored
        decomposition? (The trainer's warm gate ORs this with
        ``warm_start_basis`` — an env-selected impl deliberately does
        NOT auto-warm, preserving the legacy contract.)"""
        return self.decomp_impl in _WARM_IMPLS

    def init(self):
        """Initial state: identity factors (reference initializes running
        averages at identity, inv.py:82-90), zero decompositions
        (eigen.py:100-107)."""
        assert self.plan is not None, 'call setup() first'
        plan = self.plan
        factors, dzero = {}, {}
        for bdim in plan.bucket_dims:
            b = plan.buckets[bdim]
            factors[str(bdim)] = jnp.broadcast_to(
                jnp.eye(bdim, dtype=jnp.float32),
                (b.n_rows, bdim, bdim))
        if self.method == 'eigh':
            decomp = {
                'evals': {str(d): jnp.zeros(
                    (plan.buckets[d].n_rows, d), jnp.float32)
                    for d in plan.bucket_dims},
                'evecs': {str(d): jnp.zeros(
                    (plan.buckets[d].n_rows, d, d), jnp.float32)
                    for d in plan.bucket_dims},
            }
            if self.ekfac:
                decomp['scales'] = self._zero_scales()
        else:
            decomp = {
                'invs': {str(d): jnp.zeros(
                    (plan.buckets[d].n_rows, d, d), jnp.float32)
                    for d in plan.bucket_dims},
            }
        return KFACState(step=jnp.zeros((), jnp.int32), factors=factors,
                         decomp=decomp, comm_err=self._zero_comm_err())

    @property
    def _tracks_comm_err(self):
        """Does this config carry an error-feedback residual? Only the
        lossy-wire MPD stats reduce compensates (the gathers have one
        contributor per row — nothing accumulates to feed back)."""
        return (self.comm_precision != 'fp32'
                and self.stats_reduce == 'pmean')

    def _zero_comm_err(self):
        """Fresh EF residual: zeros shaped like the stats stack PER
        DEVICE — globally ``[num_devices * n_rows, D, D]`` sharded over
        the kfac axis, so each device's shard is its own residual for
        the full stacked stats it contributes to the reduce."""
        if not self._tracks_comm_err:
            return None
        return {str(d): jnp.zeros(
                    (self.plan.num_devices * self.plan.buckets[d].n_rows,
                     d, d), jnp.float32)
                for d in self.plan.bucket_dims}

    def state_pspecs(self, axis_name=None):
        """PartitionSpecs matching the state layout: factor rows sharded
        over the kfac axis; decompositions sharded in comm_pred mode,
        replicated (post-gather) in comm_inverse mode; the EF residual
        (per-device error state) sharded like the factors."""
        axis_name = axis_name or self.axis_name
        sharded = P(axis_name)
        replicated = P()
        factors = {k: sharded for k in (str(d) for d in self.plan.bucket_dims)}
        dspec = sharded if self.comm_mode == 'pred' else replicated
        decomp = jax.tree.map(lambda _: dspec, self._decomp_structure())
        comm_err = ({k: sharded for k in factors}
                    if self._tracks_comm_err else None)
        return KFACState(step=replicated, factors=factors, decomp=decomp,
                         comm_err=comm_err)

    def _zero_scales(self, local=False):
        # replicated layout: one row per group member; comm_pred layout:
        # device-major local slots (K per device), like the factor rows.
        # ``local=True`` builds the PER-DEVICE shape — required when the
        # default is materialized inside the shard_map trace (the
        # pre-ekfac-checkpoint fallback in step); the global shape is
        # the host-side init()/state layout
        if self.comm_mode == 'pred':
            mult = 1 if local else self.plan.num_devices
            return {f'g{gi}': jnp.zeros(
                        (mult * pg.local_member.shape[1],
                         pg.dg, pg.da), jnp.float32)
                    for gi, pg in enumerate(self.plan.pred_groups)}
        return {f'g{gi}': jnp.zeros(
                    (len(pg.layer_idx), pg.dg, pg.da), jnp.float32)
                for gi, pg in enumerate(self.plan.pred_groups)}

    def _decomp_structure(self):
        if self.method == 'eigh':
            out = {'evals': {str(d): 0 for d in self.plan.bucket_dims},
                   'evecs': {str(d): 0 for d in self.plan.bucket_dims}}
            if self.ekfac:
                out['scales'] = {
                    f'g{gi}': 0
                    for gi in range(len(self.plan.pred_groups))}
            return out
        return {'invs': {str(d): 0 for d in self.plan.bucket_dims}}

    # -- host-side gating (trainer chooses compiled step variants) --------

    def should_update_factors(self, step: int) -> bool:
        return self.hook_enabled and step % self.fac_update_freq == 0

    def should_update_inverse(self, step: int) -> bool:
        return step % self.kfac_update_freq == 0

    def should_update_basis(self, step: int,
                            last_full_step: Optional[int] = None) -> bool:
        """Full eigendecomposition vs eigenvalue-only refresh at an
        inverse-update step (meaningful only when basis_update_freq is
        set and should_update_inverse(step) holds).

        Staleness-based (steps since the last full decomposition), not
        step-modulo: a modulo rule would alias against kfac_update_freq
        (full eigh only at the lcm of the two) and silently starve the
        basis when KFACParamScheduler rescales kfac_update_freq.
        """
        if self.basis_update_freq is None or last_full_step is None:
            return True
        return step - last_full_step >= self.basis_update_freq

    # -- the step ---------------------------------------------------------

    def step(self, state: KFACState, grads, acts=None, gs=None,
             hyper: Optional[KFACHyperParams] = None, *,
             update_factors: bool = True, update_inverse: bool = True,
             update_basis: bool = True, warm_basis: bool = False,
             factors_only: bool = False, stagger_update: bool = False,
             prefetch: bool = False, axis_name: str = '__default__'):
        """One K-FAC step: (state, grads, captured stats) ->
        (preconditioned grads, new state).

        Pure and traceable; call inside jit / shard_map. ``update_factors``
        and ``update_inverse`` are STATIC — the trainer picks them from
        ``should_update_*`` (the steps-%-freq gating of
        kfac_preconditioner_base.py:198-213 moved to the host).

        ``prefetch`` (STATIC; requires ``comm_prefetch=True``) applies
        PR 4's double-buffer to a FULL inverse update: the freshly
        gathered decomposition is published for the NEXT step while this
        step preconditions with the previous stored table — the
        CommunicateInverse gather has no same-step consumer. The trainer
        sets it only once a prior decomposition exists (a cold state
        would precondition with zeros).

        ``stagger_update`` (STATIC; requires ``stagger=True``) replaces
        the windowed full refresh: cohort ``state.step % kfac_update_freq``
        (a TRACED index — one compiled program serves every cohort) is
        decomposed and merged into the stored decomposition for the NEXT
        step, while THIS step preconditions with the previous table (the
        double-buffered publish). ``update_inverse`` is ignored when set.
        The stored decomposition must already be populated (the trainer
        runs one full decomposition first); a cold state would
        precondition with zeros.

        Parity with step() (kfac_preconditioner_base.py:185-230): factor
        stats + running-avg update (+ pmean for MPD), decomposition on the
        local shard, gather/owner-pred per comm mode, KL-clipped write-back.
        """
        assert self.plan is not None, 'call setup() first'
        plan = self.plan
        if axis_name == '__default__':
            axis_name = self.axis_name
        if hyper is None:
            hyper = KFACHyperParams(lr=jnp.float32(self.lr),
                                    damping=jnp.float32(self.damping))
        damping = jnp.asarray(hyper.damping, jnp.float32)
        lr = jnp.asarray(hyper.lr, jnp.float32)

        factors = state.factors
        decomp = state.decomp
        comm_err = state.comm_err

        if update_factors and not self.exclude_compute_factor:
            reduce = self.stats_reduce
            if self.exclude_communicate_factor:
                reduce = 'local'
            cap_impl = self.resolved_capture_impl
            if (cap_impl == 'pallas' and reduce == 'local'
                    and plan.num_devices == 1):
                # single-device local stats: the whole capture chain
                # (patch-extract -> factor GEMM -> EMA) collapses into
                # one fused kernel per factor — the UpdateFactors pass
                # disappears from the trace by design (its cost is
                # modeled under ComputeFactor_pallas in perfmodel.py)
                with jax.named_scope('kfac.ComputeFactor'):
                    factors = engine.update_factors_fused(
                        plan, factors, acts, gs, self.batch_averaged,
                        self.factor_decay)
            else:
                # named scopes mirror the reference's phase taxonomy
                # (exclude_parts names) so xprof traces attribute time
                # the same way scripts/time_breakdown.py does
                with jax.named_scope('kfac.ComputeFactor'):
                    a_list, g_list = engine.compute_layer_stats(
                        plan, acts, gs, self.batch_averaged,
                        capture_impl=cap_impl)
                    stats = engine.stack_stats(plan, a_list, g_list)
                with jax.named_scope('kfac.UpdateFactors'):
                    # the pmean inside carries its own CommunicateFactor
                    # scope
                    extra = (self._mesh_plan.extra_reduce()
                             if self._mesh_plan is not None else ())
                    factors, comm_err = engine.update_factors(
                        plan, factors, stats, self.factor_decay, reduce,
                        axis_name, comm_precision=self.comm_precision,
                        comm_err=comm_err, capture_impl=cap_impl,
                        extra_reduce=extra)
            if self.health is not None and comm_err is not None:
                # a non-finite residual row resets to zero (the always-
                # safe EF state: feedback is a correction, never load-
                # bearing) instead of re-injecting NaN into every later
                # stats reduce
                with jax.named_scope('kfac.HealthGuard.comm_err'):
                    comm_err = engine.where_finite_rows(
                        comm_err,
                        {k: jnp.zeros_like(v) for k, v in comm_err.items()})
            if self.health is not None:
                # non-finite EMA rows keep the last good factor; a row
                # whose STORED value is already corrupt (silent data
                # corruption) re-initializes to the identity and
                # re-accumulates — pass-through when everything is finite
                with jax.named_scope('kfac.HealthGuard.factors'):
                    factors = engine.where_finite_rows(
                        factors, state.factors, reinit_identity=True)
            # SDC drill: corrupt a stored factor block AFTER the guard,
            # so the corruption lands in the state exactly as a flipped
            # bit would (tests/test_faults.py heal drill)
            factors = faults.corrupt_factors(self._faults, state.step,
                                             factors)

        if factors_only:
            # accumulate statistics but leave gradients untouched — used
            # before the first decomposition exists (an all-zero decomp
            # would zero the gradients)
            return grads, state.replace(step=state.step + 1,
                                        factors=factors, comm_err=comm_err)

        if self.exclude_compute_inverse:
            # ablation: no decomposition -> grads pass through
            # (kfac_preconditioner_base.py:206-226)
            return grads, state.replace(step=state.step + 1,
                                        factors=factors, comm_err=comm_err)

        if stagger_update:
            update_inverse = False  # stagger replaces the windowed refresh

        scales_prev = None
        if self.ekfac:
            # a state restored from a pre-ekfac checkpoint has no
            # 'scales' key: default to zeros so the pred path's validity
            # guard falls back to the Kronecker denominator instead of
            # crashing in the scale update/rotation
            scales_prev = decomp.get('scales')
            if scales_prev is None:
                scales_prev = self._zero_scales(local=True)
        if update_inverse:
            if self.method == 'eigh' and not update_basis:
                # eigenvalue-only refresh in the retained eigenbasis
                decomp_prev = decomp
                with jax.named_scope('kfac.ComputeInverse.refresh'):
                    decomp = engine.refresh_decomposition(
                        plan, factors, decomp_prev, self.eps, axis_name,
                        self.comm_mode,
                        communicate=not self.exclude_communicate_inverse,
                        comm_precision=self.comm_precision)
                if self.health is not None:
                    with jax.named_scope('kfac.HealthGuard.decomp'):
                        decomp = engine.guard_decomposition(
                            decomp, decomp_prev, 'eigh')
                # basis unchanged -> stored moments stay valid as-is
            else:
                basis_local = invs_prev = None
                if (self.warm_start_basis or self.warm_impl) and warm_basis:
                    # warm_basis is STATIC, set by the trainer only after
                    # a full decomposition exists (a zero basis would
                    # silently corrupt the rotated eigh problem; a zero
                    # inverse seed is caught by the NS residual gate).
                    # An explicit iterative decomp_impl implies warm
                    # seeding — that is what makes its rung cheap
                    if self.method == 'eigh':
                        basis_local = engine.local_evecs(
                            plan, decomp, axis_name, self.comm_mode)
                    else:
                        invs_prev = engine.local_invs(
                            plan, decomp, axis_name, self.comm_mode)
                with jax.named_scope('kfac.ComputeInverse'):
                    decomp_local = engine.compute_decomposition(
                        plan, factors, damping, self.method, self.eps,
                        axis_name, basis_local=basis_local,
                        warm_sweeps=self.warm_sweeps,
                        invs_prev_local=invs_prev,
                        impl=self.resolved_decomp_impl)
                # chaos drill: simulated eigh/Cholesky blowup, injected
                # BEFORE the guard so the guard is what survives it
                decomp_local = faults.corrupt_decomposition(
                    self._faults, state.step, decomp_local)
                if self.health is not None:
                    # a non-finite decomposition row falls back to the
                    # last good one (identity when cold) instead of
                    # poisoning every later preconditioned gradient;
                    # guarding PRE-gather/rotation keeps the E-KFAC
                    # moment transport on a finite basis too
                    with jax.named_scope('kfac.HealthGuard.decomp'):
                        decomp_local = engine.guard_decomposition(
                            decomp_local,
                            engine.local_decomposition(
                                plan, decomp, axis_name, self.comm_mode,
                                self.method),
                            self.method)
                if self.comm_mode == 'inverse':
                    with jax.named_scope('kfac.CommunicateInverse'):
                        new_decomp = engine.gather_decomposition(
                            plan, decomp_local, axis_name,
                            communicate=not self.exclude_communicate_inverse,
                            comm_precision=self.comm_precision)
                    if self.ekfac:
                        # the EMA'd moments live in the OLD basis: carry
                        # them across the basis change by the squared-
                        # overlap transport (exact for sign flips /
                        # unmoved bases, mass-preserving otherwise)
                        with jax.named_scope('kfac.EkfacScales.rotate'):
                            scales_prev = engine.rotate_ekfac_scales(
                                plan, scales_prev, decomp, new_decomp)
                    decomp = new_decomp
                else:
                    if self.ekfac:
                        # comm_pred: rotate each local slot by its own
                        # old/new basis rows (owner-local transport)
                        with jax.named_scope('kfac.EkfacScales.rotate'):
                            scales_prev = engine.rotate_ekfac_scales_local(
                                plan, scales_prev,
                                engine.local_evecs(plan, decomp, axis_name,
                                                   'pred'),
                                decomp_local['evecs'], axis_name)
                    decomp = decomp_local
        if self.ekfac:
            decomp = dict(decomp)
            decomp['scales'] = scales_prev
            if (update_factors and acts is not None
                    and not self.exclude_compute_factor):
                reduce = ('local' if self.exclude_communicate_factor
                          else self.stats_reduce)
                with jax.named_scope('kfac.EkfacScales'):
                    if self.comm_mode == 'pred':
                        # owner-local moments: zero scale communication
                        decomp['scales'] = engine.update_ekfac_scales_local(
                            plan, decomp, acts, gs, self.batch_averaged,
                            scales_prev, self.factor_decay, axis_name)
                    else:
                        decomp['scales'] = engine.update_ekfac_scales(
                            plan, decomp, acts, gs, self.batch_averaged,
                            scales_prev, self.factor_decay, reduce,
                            axis_name, comm_precision=self.comm_precision)
                if self.health is not None:
                    # non-finite moment rows keep the (rotated) previous
                    # moments; the pred path's zero-validity guard covers
                    # the cold case already
                    with jax.named_scope('kfac.HealthGuard.scales'):
                        decomp['scales'] = engine.where_finite_rows(
                            decomp['scales'], scales_prev)

        # double-buffer: staggered steps precondition with the PREVIOUS
        # stored table while the freshly decomposed cohort is merged into
        # the state for the next step — the cohort eigh/gather has no
        # same-step consumer, so XLA can overlap it with the pred einsums
        pred_decomp = decomp
        if prefetch and update_inverse:
            # comm_prefetch: the same trade for a FULL inverse update —
            # publish the freshly gathered table for the NEXT step,
            # precondition THIS step with the stored one (one step of
            # staleness; the gather overlaps the pred einsums)
            assert self.comm_prefetch, \
                'prefetch requires KFAC(comm_prefetch=True)'
            pred_decomp = state.decomp
        if stagger_update:
            cohorts = self._cohorts
            assert cohorts is not None, \
                'stagger_update requires KFAC(stagger=True) + setup()'
            cohort_idx = jnp.mod(jnp.asarray(state.step, jnp.int32),
                                 jnp.int32(cohorts.num_cohorts))
            if self.decomp_shard:
                # tentpole: the cohort's rows decompose balanced across
                # ALL devices (plan.build_decomp_shard) — the shard
                # exchange's two gathers carry the kfac.DecompComm
                # scope for the HLO byte ledger
                shard = self._shard_plan
                assert shard is not None, \
                    'decomp_shard requires setup() (rebase_cohorts)'
                with jax.named_scope('kfac.ComputeInverse.stagger'):
                    shard_new = engine.compute_shard_decomposition(
                        plan, cohorts, shard, factors, cohort_idx,
                        damping, self.method, self.eps, axis_name,
                        impl=self.resolved_decomp_impl,
                        decomp_prev=decomp, comm_mode=self.comm_mode,
                        warm_sweeps=self.warm_sweeps,
                        comm_precision=self.comm_precision)
                # chaos drill parity: blowups injected BEFORE the
                # merge's per-row screen, which is what heals them
                shard_new = faults.corrupt_decomposition(
                    self._faults, state.step, shard_new)
                with jax.named_scope('kfac.CommunicateInverse.stagger'):
                    decomp = engine.merge_shard_decomposition(
                        plan, shard, decomp, shard_new, cohort_idx,
                        axis_name, self.comm_mode, self.method,
                        guard=self.health is not None,
                        comm_precision=self.comm_precision)
            else:
                with jax.named_scope('kfac.ComputeInverse.stagger'):
                    cohort_new = engine.compute_cohort_decomposition(
                        plan, cohorts, factors, cohort_idx, damping,
                        self.method, self.eps, axis_name,
                        impl=self.resolved_decomp_impl,
                        decomp_prev=(decomp if self.warm_impl else None),
                        comm_mode=self.comm_mode,
                        warm_sweeps=self.warm_sweeps)
                # chaos drill parity with the full path: blowups
                # injected BEFORE the merge's per-row screen, which is
                # what heals them
                cohort_new = faults.corrupt_decomposition(
                    self._faults, state.step, cohort_new)
                with jax.named_scope('kfac.CommunicateInverse.stagger'):
                    decomp = engine.merge_cohort_decomposition(
                        plan, cohorts, decomp, cohort_new, cohort_idx,
                        axis_name, self.comm_mode, self.method,
                        communicate=not self.exclude_communicate_inverse,
                        guard=self.health is not None,
                        comm_precision=self.comm_precision)

        grad_mats = [engine.layer_grad_matrix(m, grads) for m in plan.metas]
        with jax.named_scope('kfac.Precondition'):
            if self.comm_mode == 'inverse':
                preds = engine.compute_pred_replicated(
                    plan, pred_decomp, grad_mats, damping, self.method,
                    scales=pred_decomp.get('scales') if self.ekfac else None)
            else:
                preds = engine.compute_pred_local(
                    plan, pred_decomp, grad_mats, damping, self.method,
                    axis_name,
                    communicate=not self.exclude_communicate_inverse,
                    scales=pred_decomp.get('scales') if self.ekfac else None,
                    comm_precision=self.comm_precision)

        new_grads = engine.preconditioned_grads(
            plan, grads, grad_mats, preds, lr, self.kl_clip,
            skip_clip=self.exclude_communicate_inverse)
        new_state = state.replace(step=state.step + 1, factors=factors,
                                  decomp=decomp, comm_err=comm_err)
        return new_grads, new_state
