"""Deterministic fault injection for chaos-testing the health subsystem.

Faults are selected by environment variables, read ONCE at build time
(``KFAC.__init__`` / ``training.build_train_step``), so the healthy path
traces exactly the code it always traced and a configured fault fires on
an exact step of the run — reproducible down to the bit, which is what
the chaos drills (tests/test_health.py, tests/test_faults.py) assert.

In-jit faults compare the traced step counter against a static step
list, so enabling one never adds compiled step variants or host syncs:

  KFAC_FAULT_NAN_GRAD_STEP   NaN gradients at the given step(s)
  KFAC_FAULT_INF_GRAD_STEP   Inf gradients at the given step(s)
  KFAC_FAULT_STATS_STEP      NaN captured (a, g) statistics — exercises
                             the trainer's factor-statistics screen
  KFAC_FAULT_FACTOR_STEP     corrupt the leading stored factor block
                             AFTER the EMA guard — a silent-data-
                             corruption drill for the decomposition
                             guard + identity re-init heal path
  KFAC_FAULT_EIGH_STEP       non-finite decomposition output ("eigh
                             blowup") — exercises engine.guard_decomposition

Step lists accept ``"7"``, ``"3,5,9"`` and half-open ranges ``"4:8"``.

Host-side faults:

  KFAC_FAULT_SIGTERM_STEP    deliver SIGTERM to this process at the
                             given step (PreemptionGuard drill)
  KFAC_FAULT_CKPT            'truncate' -> the pickle checkpoint writes
                             half its bytes to the FINAL path (a crash
                             mid-save, pre-atomic-rename behavior);
                             'fail' -> the write dies after a partial
                             tmp file (the atomic path must leave no
                             final file behind)
"""

import dataclasses
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

ENV_NAN_GRAD = 'KFAC_FAULT_NAN_GRAD_STEP'
ENV_INF_GRAD = 'KFAC_FAULT_INF_GRAD_STEP'
ENV_STATS = 'KFAC_FAULT_STATS_STEP'
ENV_FACTOR = 'KFAC_FAULT_FACTOR_STEP'
ENV_EIGH = 'KFAC_FAULT_EIGH_STEP'
ENV_SIGTERM = 'KFAC_FAULT_SIGTERM_STEP'
ENV_CKPT = 'KFAC_FAULT_CKPT'


def parse_steps(spec: Optional[str]) -> Tuple[int, ...]:
    """``"7"`` -> (7,); ``"3,5"`` -> (3, 5); ``"4:8"`` -> (4, 5, 6, 7)."""
    if not spec:
        return ()
    out = []
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        if ':' in part:
            lo, hi = part.split(':')
            out.extend(range(int(lo), int(hi)))
        else:
            out.append(int(part))
    return tuple(sorted(set(out)))


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    nan_grad_steps: Tuple[int, ...] = ()
    inf_grad_steps: Tuple[int, ...] = ()
    stats_steps: Tuple[int, ...] = ()
    factor_steps: Tuple[int, ...] = ()
    eigh_steps: Tuple[int, ...] = ()
    sigterm_step: Optional[int] = None
    ckpt_mode: Optional[str] = None

    @property
    def any_injit(self) -> bool:
        return bool(self.nan_grad_steps or self.inf_grad_steps
                    or self.stats_steps or self.factor_steps
                    or self.eigh_steps)


def from_env() -> FaultConfig:
    """Snapshot the fault environment (call at build/setup time)."""
    sig = os.environ.get(ENV_SIGTERM)
    mode = os.environ.get(ENV_CKPT) or None
    if mode is not None and mode not in ('truncate', 'fail'):
        raise ValueError(f'{ENV_CKPT} must be "truncate" or "fail", '
                         f'got {mode!r}')
    return FaultConfig(
        nan_grad_steps=parse_steps(os.environ.get(ENV_NAN_GRAD)),
        inf_grad_steps=parse_steps(os.environ.get(ENV_INF_GRAD)),
        stats_steps=parse_steps(os.environ.get(ENV_STATS)),
        factor_steps=parse_steps(os.environ.get(ENV_FACTOR)),
        eigh_steps=parse_steps(os.environ.get(ENV_EIGH)),
        sigterm_step=int(sig) if sig else None,
        ckpt_mode=mode)


def _hit(steps: Tuple[int, ...], step):
    """Traced scalar bool: does the step counter match the static list?"""
    h = jnp.zeros((), bool)
    for s in steps:
        h = jnp.logical_or(h, step == s)
    return h


def _poison(tree, hit, value):
    def leaf(x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
            return x
        return jnp.where(hit, jnp.asarray(value, jnp.asarray(x).dtype), x)
    return jax.tree.map(leaf, tree)


def corrupt_grads(cfg: FaultConfig, step, grads):
    """NaN/Inf gradient injection at the configured step(s)."""
    if cfg.nan_grad_steps:
        grads = _poison(grads, _hit(cfg.nan_grad_steps, step), jnp.nan)
    if cfg.inf_grad_steps:
        grads = _poison(grads, _hit(cfg.inf_grad_steps, step), jnp.inf)
    return grads


def corrupt_captured(cfg: FaultConfig, step, acts, gs):
    """NaN injection into the captured (a, g) statistics."""
    if cfg.stats_steps and acts is not None:
        hit = _hit(cfg.stats_steps, step)
        acts = _poison(acts, hit, jnp.nan)
        gs = _poison(gs, hit, jnp.nan)
    return acts, gs


def corrupt_factors(cfg: FaultConfig, step, factors):
    """Corrupt the LEADING row of every factor bucket (one bad block per
    bucket — the per-row guard granularity is the point of the drill)."""
    if not cfg.factor_steps:
        return factors
    hit = _hit(cfg.factor_steps, step)
    return {k: v.at[0].set(jnp.where(hit, jnp.nan, v[0]))
            for k, v in factors.items()}


def corrupt_decomposition(cfg: FaultConfig, step, decomp):
    """Non-finite decomposition output (simulated eigh blowup)."""
    if not cfg.eigh_steps:
        return decomp
    return _poison(decomp, _hit(cfg.eigh_steps, step), jnp.nan)


_SIGTERM_FIRED = False


def reset_sigterm_fault():
    """Re-arm the one-shot SIGTERM fault (test isolation)."""
    global _SIGTERM_FIRED
    _SIGTERM_FIRED = False


def maybe_sigterm(cfg: Optional[FaultConfig], step: int) -> None:
    """Host-side: deliver SIGTERM to this process once, at the
    configured step (the PreemptionGuard chaos drill)."""
    global _SIGTERM_FIRED
    if (cfg is None or cfg.sigterm_step is None or _SIGTERM_FIRED
            or step != cfg.sigterm_step):
        return
    _SIGTERM_FIRED = True
    import signal
    os.kill(os.getpid(), signal.SIGTERM)


def checkpoint_fault_mode() -> Optional[str]:
    """Live read of the checkpoint-write fault (the save path consults
    it per call so a drill can toggle it between epochs)."""
    return os.environ.get(ENV_CKPT) or None
