"""Deterministic fault injection for chaos-testing the health subsystem.

Faults are selected by environment variables, read ONCE at build time
(``KFAC.__init__`` / ``training.build_train_step``), so the healthy path
traces exactly the code it always traced and a configured fault fires on
an exact step of the run — reproducible down to the bit, which is what
the chaos drills (tests/test_health.py, tests/test_faults.py) assert.

In-jit faults compare the traced step counter against a static step
list, so enabling one never adds compiled step variants or host syncs:

  KFAC_FAULT_NAN_GRAD_STEP   NaN gradients at the given step(s)
  KFAC_FAULT_INF_GRAD_STEP   Inf gradients at the given step(s)
  KFAC_FAULT_STATS_STEP      NaN captured (a, g) statistics — exercises
                             the trainer's factor-statistics screen
  KFAC_FAULT_FACTOR_STEP     corrupt the leading stored factor block
                             AFTER the EMA guard — a silent-data-
                             corruption drill for the decomposition
                             guard + identity re-init heal path
  KFAC_FAULT_EIGH_STEP       non-finite decomposition output ("eigh
                             blowup") — exercises engine.guard_decomposition

Step lists accept ``"7"``, ``"3,5,9"`` and half-open ranges ``"4:8"``.

Host-side faults:

  KFAC_FAULT_SIGTERM_STEP    deliver SIGTERM to this process at the
                             given step (PreemptionGuard drill)
  KFAC_FAULT_CKPT            'truncate' -> the pickle checkpoint writes
                             half its bytes to the FINAL path (a crash
                             mid-save, pre-atomic-rename behavior);
                             'fail' -> the write dies after a partial
                             tmp file (the atomic path must leave no
                             final file behind);
                             'eio_once' -> the FIRST write raises a
                             transient EIO, later ones succeed (the
                             retry-policy drill)
  KFAC_FAULT_HANG_STEP       block the host forever at this step (the
                             step-watchdog drill)
  KFAC_FAULT_SLOW_STEP       sleep KFAC_FAULT_SLOW_SECS (default 1.0)
                             per listed step (the straggler-governor
                             drill; step-list syntax)
  KFAC_FAULT_CRASH_STEP      die at this step: KFAC_FAULT_CRASH_MODE
                             'exit' (default, os._exit(CRASH_RC=113))
                             or 'sigkill' (SIGKILL to self — the
                             supervisor restart drill; on ONE host of a
                             pod this doubles as the PEER-DEATH drill —
                             the survivors' heartbeats must detect it)
  KFAC_FAULT_HB_STOP_STEP    stop publishing heartbeats at this step
                             while the trainer keeps running — the
                             HEARTBEAT-LOSS drill: the peers declare
                             this host dead and shrink around it, and
                             its own pod supervisor must fence it
                             (resilience/heartbeat.py consumes this via
                             PeerHeartbeat.tick)
  KFAC_FAULT_DATA_STEP       the data loader raises a transient EIO at
                             this batch index, once (next-batch retry
                             drill)
  KFAC_FAULT_NET_*           deterministic network chaos on the pod's
                             side channels: seeded drop/delay/duplicate/
                             reorder schedules plus a time-windowed
                             (src, dst) partition matrix, applied by
                             resilience.chaos_net.ChaosTransport around
                             the heartbeat transports and consulted by
                             the pod supervisor's protocol-file readers
                             (the partition drill; see chaos_net.py for
                             the full sub-contract)
  KFAC_FAULT_COORD_*         deterministic COORDINATION-BACKEND chaos:
                             seeded op failures/outage windows, torn
                             and stale reads, spurious CAS conflicts,
                             premature lease expiry — injected by
                             coord.chaos.ChaosBackend around whichever
                             backend (POSIX dir / TCP KV) the pod
                             protocols and the job queue run on (see
                             coord/chaos.py for the full sub-contract)
  KFAC_FAULT_ONCE_DIR        directory of cross-RESTART one-shot
                             tokens: with it set, hang/crash faults
                             fire only in the first process that
                             reaches them, so a supervised relaunch
                             runs clean (without it a restarted trainer
                             replaying the faulted step would fault
                             again, forever)

``from_env`` is STRICT: any ``KFAC_FAULT_*`` variable it does not know,
or a malformed step spec, raises ``ValueError`` at build time — a typo'd
drill must fail loudly, not pass vacuously with the fault never armed.
"""

import dataclasses
import errno
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

ENV_NAN_GRAD = 'KFAC_FAULT_NAN_GRAD_STEP'
ENV_INF_GRAD = 'KFAC_FAULT_INF_GRAD_STEP'
ENV_STATS = 'KFAC_FAULT_STATS_STEP'
ENV_FACTOR = 'KFAC_FAULT_FACTOR_STEP'
ENV_EIGH = 'KFAC_FAULT_EIGH_STEP'
ENV_SIGTERM = 'KFAC_FAULT_SIGTERM_STEP'
ENV_CKPT = 'KFAC_FAULT_CKPT'
ENV_HANG = 'KFAC_FAULT_HANG_STEP'
ENV_SLOW = 'KFAC_FAULT_SLOW_STEP'
ENV_SLOW_SECS = 'KFAC_FAULT_SLOW_SECS'
ENV_CRASH = 'KFAC_FAULT_CRASH_STEP'
ENV_CRASH_MODE = 'KFAC_FAULT_CRASH_MODE'
ENV_DATA = 'KFAC_FAULT_DATA_STEP'
ENV_ONCE_DIR = 'KFAC_FAULT_ONCE_DIR'
# defined by the (jax-free) heartbeat module, registered here so the
# strict from_env knows the drill exists
from kfac_pytorch_tpu.resilience.heartbeat import ENV_HB_STOP  # noqa: E402
# network chaos (drop/delay/dup/reorder schedules + the time-windowed
# partition matrix): defined and CONSUMED by the jax-free
# resilience.chaos_net layer, registered here so the strict from_env
# validates the whole drill surface at build time
from kfac_pytorch_tpu.resilience.chaos_net import NET_ENVS  # noqa: E402
# coordination-backend chaos (op failures, torn/stale reads, CAS
# conflicts, lease expiry, outage windows): defined and CONSUMED by the
# jax-free coord.chaos layer, registered here so the strict from_env
# validates the whole drill surface at build time
from kfac_pytorch_tpu.coord.chaos import COORD_ENVS  # noqa: E402
# ... and the object-store chaos lanes (torn uploads, partial/stale
# reads, 503 windows, lost put acks): defined and CONSUMED by the
# jax-free store.chaos layer, registered here for the same reason
from kfac_pytorch_tpu.store.chaos import STORE_ENVS  # noqa: E402
# the central env registry: the strict check derives its known-set
# from the declarations, so "documented" and "accepted" can never
# drift apart (kfac-lint's env-contract rule checks the read sites
# against the same file, statically)
from kfac_pytorch_tpu import envspec  # noqa: E402

KNOWN_ENVS = envspec.declared('KFAC_FAULT_')

# the registry and the consumers are mutually pinned at import time: a
# drill env consumed here (or by chaos_net / coord.chaos / heartbeat)
# but not declared in envspec.py — or declared there but consumed by
# nothing — is a contract hole that must fail the build, not pass
# vacuously with the fault never armed
_CONSUMED = frozenset({
    ENV_NAN_GRAD, ENV_INF_GRAD, ENV_STATS, ENV_FACTOR, ENV_EIGH,
    ENV_SIGTERM, ENV_CKPT, ENV_HANG, ENV_SLOW, ENV_SLOW_SECS, ENV_CRASH,
    ENV_CRASH_MODE, ENV_DATA, ENV_ONCE_DIR, ENV_HB_STOP,
}) | NET_ENVS | COORD_ENVS | STORE_ENVS
if _CONSUMED != KNOWN_ENVS:  # pragma: no cover — import-time contract
    raise RuntimeError(
        'faults/envspec drift: undeclared drill env(s) '
        f'{sorted(_CONSUMED - KNOWN_ENVS)}, declared-but-unconsumed '
        f'{sorted(KNOWN_ENVS - _CONSUMED)}; fix '
        'kfac_pytorch_tpu/envspec.py')

# rc of the 'exit'-mode crash fault: distinct from Python's generic 1
# and from the watchdog's RC_HANG (114) so supervisor logs attribute it
CRASH_RC = 113


def parse_steps(spec: Optional[str], env: str = '?') -> Tuple[int, ...]:
    """``"7"`` -> (7,); ``"3,5"`` -> (3, 5); ``"4:8"`` -> (4, 5, 6, 7)."""
    if not spec:
        return ()
    out = []
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        try:
            if ':' in part:
                lo, hi = part.split(':')
                out.extend(range(int(lo), int(hi)))
            else:
                out.append(int(part))
        except ValueError:
            raise ValueError(
                f'{env}: malformed step spec {spec!r} (part {part!r}); '
                'accepted: "7", "3,5,9", "4:8"') from None
    return tuple(sorted(set(out)))


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    nan_grad_steps: Tuple[int, ...] = ()
    inf_grad_steps: Tuple[int, ...] = ()
    stats_steps: Tuple[int, ...] = ()
    factor_steps: Tuple[int, ...] = ()
    eigh_steps: Tuple[int, ...] = ()
    sigterm_step: Optional[int] = None
    ckpt_mode: Optional[str] = None
    hang_step: Optional[int] = None
    slow_steps: Tuple[int, ...] = ()
    slow_secs: float = 1.0
    crash_step: Optional[int] = None
    crash_mode: str = 'exit'
    data_step: Optional[int] = None

    @property
    def any_injit(self) -> bool:
        return bool(self.nan_grad_steps or self.inf_grad_steps
                    or self.stats_steps or self.factor_steps
                    or self.eigh_steps)


def _int_env(env: str) -> Optional[int]:
    raw = os.environ.get(env)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f'{env} must be an integer step, '
                         f'got {raw!r}') from None


def _float_env(env: str, default: float) -> float:
    raw = os.environ.get(env)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f'{env} must be a number of seconds, '
                         f'got {raw!r}') from None


def from_env() -> FaultConfig:
    """Snapshot the fault environment (call at build/setup time).

    Strict: unknown ``KFAC_FAULT_*`` names and malformed values raise —
    a chaos drill whose fault silently never arms proves nothing.
    """
    unknown = sorted(k for k in os.environ
                     if k.startswith('KFAC_FAULT_') and k not in KNOWN_ENVS)
    if unknown:
        raise ValueError(
            f'unrecognized fault env var(s) {unknown}; known: '
            f'{sorted(KNOWN_ENVS)}')
    # validate-only: the heartbeat-loss drill is CONSUMED by the jax-free
    # heartbeat layer (heartbeat_from_env), not through this config — but
    # a malformed value must still fail loudly at build time like every
    # other drill, even in runs with no heartbeat configured
    _int_env(ENV_HB_STOP)
    # validate-only likewise: the network-chaos schedule is consumed by
    # resilience.chaos_net (ChaosTransport + the protocol-file partition
    # filter), but a malformed spec must die here, at build time
    from kfac_pytorch_tpu.resilience import chaos_net as _chaos_net
    _chaos_net.from_env()
    # validate-only likewise: the coordination-backend chaos schedule is
    # consumed by coord.chaos (every backend construction site wraps
    # through maybe_wrap), but a malformed spec must die here, at build
    # time, like every other drill
    from kfac_pytorch_tpu.coord import chaos as _coord_chaos
    _coord_chaos.from_env()
    # validate-only likewise: the object-store chaos schedule is
    # consumed by store.chaos (every store construction site wraps
    # through maybe_wrap), but a malformed spec must die here too
    from kfac_pytorch_tpu.store import chaos as _store_chaos
    _store_chaos.from_env()
    mode = os.environ.get(ENV_CKPT) or None
    if mode is not None and mode not in ('truncate', 'fail', 'eio_once'):
        raise ValueError(f'{ENV_CKPT} must be "truncate", "fail" or '
                         f'"eio_once", got {mode!r}')
    crash_mode = os.environ.get(ENV_CRASH_MODE) or 'exit'
    if crash_mode not in ('exit', 'sigkill'):
        raise ValueError(f'{ENV_CRASH_MODE} must be "exit" or "sigkill", '
                         f'got {crash_mode!r}')
    return FaultConfig(
        nan_grad_steps=parse_steps(os.environ.get(ENV_NAN_GRAD),
                                   ENV_NAN_GRAD),
        inf_grad_steps=parse_steps(os.environ.get(ENV_INF_GRAD),
                                   ENV_INF_GRAD),
        stats_steps=parse_steps(os.environ.get(ENV_STATS), ENV_STATS),
        factor_steps=parse_steps(os.environ.get(ENV_FACTOR), ENV_FACTOR),
        eigh_steps=parse_steps(os.environ.get(ENV_EIGH), ENV_EIGH),
        sigterm_step=_int_env(ENV_SIGTERM),
        ckpt_mode=mode,
        hang_step=_int_env(ENV_HANG),
        slow_steps=parse_steps(os.environ.get(ENV_SLOW), ENV_SLOW),
        slow_secs=_float_env(ENV_SLOW_SECS, 1.0),
        crash_step=_int_env(ENV_CRASH),
        crash_mode=crash_mode,
        data_step=_int_env(ENV_DATA))


def _hit(steps: Tuple[int, ...], step):
    """Traced scalar bool: does the step counter match the static list?"""
    h = jnp.zeros((), bool)
    for s in steps:
        h = jnp.logical_or(h, step == s)
    return h


def _poison(tree, hit, value):
    def leaf(x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
            return x
        return jnp.where(hit, jnp.asarray(value, jnp.asarray(x).dtype), x)
    return jax.tree.map(leaf, tree)


def corrupt_grads(cfg: FaultConfig, step, grads):
    """NaN/Inf gradient injection at the configured step(s)."""
    if cfg.nan_grad_steps:
        grads = _poison(grads, _hit(cfg.nan_grad_steps, step), jnp.nan)
    if cfg.inf_grad_steps:
        grads = _poison(grads, _hit(cfg.inf_grad_steps, step), jnp.inf)
    return grads


def corrupt_captured(cfg: FaultConfig, step, acts, gs):
    """NaN injection into the captured (a, g) statistics."""
    if cfg.stats_steps and acts is not None:
        hit = _hit(cfg.stats_steps, step)
        acts = _poison(acts, hit, jnp.nan)
        gs = _poison(gs, hit, jnp.nan)
    return acts, gs


def corrupt_factors(cfg: FaultConfig, step, factors):
    """Corrupt the LEADING row of every factor bucket (one bad block per
    bucket — the per-row guard granularity is the point of the drill)."""
    if not cfg.factor_steps:
        return factors
    hit = _hit(cfg.factor_steps, step)
    return {k: v.at[0].set(jnp.where(hit, jnp.nan, v[0]))
            for k, v in factors.items()}


def corrupt_decomposition(cfg: FaultConfig, step, decomp):
    """Non-finite decomposition output (simulated eigh blowup)."""
    if not cfg.eigh_steps:
        return decomp
    return _poison(decomp, _hit(cfg.eigh_steps, step), jnp.nan)


_SIGTERM_FIRED = False


def reset_sigterm_fault():
    """Re-arm the one-shot SIGTERM fault (test isolation)."""
    global _SIGTERM_FIRED
    _SIGTERM_FIRED = False


def maybe_sigterm(cfg: Optional[FaultConfig], step: int) -> None:
    """Host-side: deliver SIGTERM to this process once, at the
    configured step (the PreemptionGuard chaos drill)."""
    global _SIGTERM_FIRED
    if (cfg is None or cfg.sigterm_step is None or _SIGTERM_FIRED
            or step != cfg.sigterm_step):
        return
    _SIGTERM_FIRED = True
    import signal
    os.kill(os.getpid(), signal.SIGTERM)


def checkpoint_fault_mode() -> Optional[str]:
    """Live read of the checkpoint-write fault (the save path consults
    it per call so a drill can toggle it between epochs)."""
    return os.environ.get(ENV_CKPT) or None


def _claim_once(name: str) -> bool:
    """Cross-restart one-shot latch: True iff THIS process should fire
    the fault. With KFAC_FAULT_ONCE_DIR set, the first process to reach
    the fault atomically creates a token file and fires; a supervised
    relaunch replaying the same step finds the token and runs clean.
    Without the dir the fault fires every time (in-process latches still
    apply where documented)."""
    once_dir = os.environ.get(ENV_ONCE_DIR)
    if not once_dir:
        return True
    os.makedirs(once_dir, exist_ok=True)
    try:
        fd = os.open(os.path.join(once_dir, f'fired-{name}'),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.close(fd)
    return True


def maybe_hang(cfg: Optional[FaultConfig], step: int) -> None:
    """Host-side: block forever at the configured step (the step-
    watchdog drill — only the watchdog's rc-114 abort ends this)."""
    if cfg is None or cfg.hang_step is None or step != cfg.hang_step:
        return
    if not _claim_once(f'hang-{step}'):
        return
    import logging
    import time as _time
    logging.getLogger(__name__).warning(
        'CHAOS FAULT ACTIVE: %s=%d — hanging this host now', ENV_HANG,
        step)
    while True:  # pragma: no cover — the watchdog kills the process
        _time.sleep(3600)


def maybe_crash(cfg: Optional[FaultConfig], step: int) -> None:
    """Host-side: die at the configured step — 'exit' via
    ``os._exit(CRASH_RC)``, 'sigkill' via SIGKILL to self (the
    supervisor restart drill; neither runs any cleanup, by design)."""
    if cfg is None or cfg.crash_step is None or step != cfg.crash_step:
        return
    if not _claim_once(f'crash-{step}'):
        return
    import logging
    logging.getLogger(__name__).warning(
        'CHAOS FAULT ACTIVE: %s=%d mode=%s — killing this host now',
        ENV_CRASH, step, cfg.crash_mode)
    for h in logging.getLogger().handlers:
        try:
            h.flush()
        except Exception:  # noqa: BLE001 — dying anyway
            pass
    if cfg.crash_mode == 'sigkill':
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    os._exit(CRASH_RC)


def maybe_slow(cfg: Optional[FaultConfig], step: int, sleep=None) -> None:
    """Host-side: sleep ``slow_secs`` at each configured step (the
    straggler drill). ``sleep`` is injectable so a ManualClock makes the
    drill wall-clock-free."""
    if cfg is None or not cfg.slow_steps or step not in cfg.slow_steps:
        return
    if sleep is None:
        import time as _time
        sleep = _time.sleep
    sleep(cfg.slow_secs)


_DATA_FIRED = False


def reset_data_fault():
    """Re-arm the one-shot data fault (test isolation)."""
    global _DATA_FIRED
    _DATA_FIRED = False


def maybe_data_fault(index: int) -> None:
    """Host-side, live-read: raise a TRANSIENT EIO from the data loader
    at the configured batch index, once per process — the next-batch
    retry path must rebuild the epoch iterator and deliver the exact
    unfaulted batch sequence."""
    global _DATA_FIRED
    spec = os.environ.get(ENV_DATA)
    if not spec or _DATA_FIRED or index != int(spec):
        return
    _DATA_FIRED = True
    raise OSError(errno.EIO, 'injected transient data-loader fault '
                             f'({ENV_DATA}={index})')


_CKPT_EIO_FIRED = False


def reset_ckpt_fault():
    """Re-arm the one-shot eio_once checkpoint fault (test isolation)."""
    global _CKPT_EIO_FIRED
    _CKPT_EIO_FIRED = False


def claim_ckpt_eio_once() -> bool:
    """True iff the 'eio_once' transient should fire for THIS save call
    (one-shot per process)."""
    global _CKPT_EIO_FIRED
    if _CKPT_EIO_FIRED:
        return False
    _CKPT_EIO_FIRED = True
    return True
