"""Tracing and phase attribution.

The reference's tracing story is manual wall-clock phase timers
(IO/FW+BW/COMM/KFAC/UPDATE, examples/pytorch_cifar10_resnet.py:289-339)
plus the --exclude-parts subtraction method (kfac_preconditioner_base.py:
96-99, consumed by scripts/parse_logs.py:44-73). Under jit the phases fuse
into one program, so the TPU equivalents are:

- :func:`trace` — a jax.profiler context writing an XLA trace (Perfetto /
  TensorBoard viewable) for true on-chip phase timing;
- :func:`exclude_parts_breakdown` — the subtraction method automated:
  time the jitted step once per ablation flag set and difference the
  means (this is the reference's attribution method, and it works under
  fusion because each ablation compiles to a smaller program).
"""

import contextlib
import time

import jax
import numpy as np

PHASES = ('ComputeFactor', 'CommunicateFactor', 'ComputeInverse',
          'CommunicateInverse')


@contextlib.contextmanager
def trace(log_dir):
    """jax.profiler trace context — the on-chip replacement for the manual
    phase timers."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def host_fence(out):
    """Force completion of every execution dispatched so far by pulling a
    tiny piece of ``out`` to the host — THE execution fence for this
    framework's timing code.

    ``jax.block_until_ready`` does not fence execution on the tunneled
    TPU platform (measured 2026-07-31, scripts/check_eigh_onchip.py: a
    multi-second eigh 'blocked' in 0.15 ms while a forced transfer took
    the full compute time). A host transfer cannot complete before the
    producing computation has run, and a TPU core executes programs in
    submission order, so fetching from the LAST dispatched program's
    output fences all of them. Only scalar-sized slices travel, keeping
    wire time out of the measurement.

    On a multi-device mesh the fetch covers EVERY addressable shard of
    the last leaf — fencing one device would let peer devices'
    post-collective epilogue still be in flight (and ``np.asarray`` of a
    non-fully-replicated sharded array would raise rather than fence).
    Multi-host scope: each process fences its OWN addressable devices;
    remote hosts' devices are fenced by their own process's call."""
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, 'shape')]
    if not leaves:
        return jax.block_until_ready(out)
    x = leaves[-1]
    shards = getattr(x, 'addressable_shards', None)
    if shards is not None:
        # an EMPTY list (multi-host leaf with no local shard) correctly
        # fences nothing — this process has no device work to wait on
        for s in shards:
            d = s.data
            np.asarray(d[(slice(0, 1),) * getattr(d, 'ndim', 0)])
    else:
        np.asarray(x[(slice(0, 1),) * getattr(x, 'ndim', 0)])


def fence_rtt(out, samples=3):
    """Measure the pure host<->device round-trip cost of :func:`host_fence`
    when nothing is pending (call right after a fence) — subtract it from
    per-iteration timings so tunnel latency doesn't masquerade as step
    time."""
    t0 = time.perf_counter()
    for _ in range(samples):
        host_fence(out)
    return (time.perf_counter() - t0) / samples


def time_steps(step_fn, state, batch, iters=30, warmup=5, kw_fn=None,
               tracer=None, **kw):
    """Mean/std steady-state iteration time (the SPEED-mode measurement,
    reference :333-344). Fences each iteration via :func:`host_fence` and
    subtracts the measured idle round-trip so per-iter times reflect
    device execution, not tunnel latency.

    kw_fn: optional ``kw_fn(i) -> dict`` of per-iteration step kwargs
    (e.g. a stepped LR schedule); merged over ``**kw``.
    tracer: optional ``obs.trace.TraceRecorder`` — each timed iteration
    is recorded as a ``bench.iter`` span (RTT-corrected duration, the
    same number that enters the mean), so a SPEED run leaves a
    per-iteration trace next to its one-line summary.
    """
    def kwargs(i):
        return {**kw, **(kw_fn(i) if kw_fn else {})}

    for i in range(warmup):
        state, m = step_fn(state, batch, **kwargs(i))
    host_fence(m)
    rtt = fence_rtt(m)
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        state, m = step_fn(state, batch, **kwargs(warmup + i))
        host_fence(m)
        t = max(time.perf_counter() - t0 - rtt, 0.0)
        times.append(t)
        if tracer is not None:
            tracer.complete('bench.iter', t, cat='bench', i=i)
    return float(np.mean(times)), float(np.std(times)), state


def speed_report(log, step_fn, state, batch, units_per_iter,
                 unit='tokens/sec', iters=60, warmup=5, kw_fn=None,
                 tracer=None, **kw):
    """The SPEED-mode measurement + log line shared by the example
    trainers: steady-state iteration time via :func:`time_steps`, one
    canonical format (scripts/parse_logs.py parses it). Pass the REAL
    per-iteration work in ``units_per_iter`` (e.g. actual batch rows x
    sequence length — not the requested batch size, which a small
    dataset may silently truncate). Returns the advanced state."""
    mean, std, state = time_steps(step_fn, state, batch, iters=iters,
                                  warmup=warmup, kw_fn=kw_fn,
                                  tracer=tracer, **kw)
    log.info('SPEED: iter time %.4f +- %.4f s (%s %.1f)',
             mean, std, unit, units_per_iter / mean)
    return state


def exclude_parts_breakdown(make_step, batch, iters=20, **kw):
    """Attribute per-phase cost by ablation subtraction.

    ``make_step(exclude_parts) -> (step_fn, fresh_state)`` builds a step
    with the given phases excluded plus a matching fresh train state.
    Returns ``{phase: seconds}`` with 'Total' and the subtraction-derived
    per-phase costs (cumulative ablation, reference parse_logs.py:44-73).
    """
    results = {}
    excluded = []
    step, state = make_step('')
    t_full, _, _ = time_steps(step, state, batch, iters=iters, **kw)
    results['Total'] = t_full
    prev = t_full
    for phase in ('CommunicateInverse', 'ComputeInverse',
                  'CommunicateFactor', 'ComputeFactor'):
        excluded.append(phase)
        step, state = make_step(','.join(excluded))
        t, _, _ = time_steps(step, state, batch, iters=iters, **kw)
        results[phase] = max(prev - t, 0.0)
        prev = t
    results['Rest'] = prev
    return results
