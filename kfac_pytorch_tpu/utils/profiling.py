"""Tracing and phase attribution.

The reference's tracing story is manual wall-clock phase timers
(IO/FW+BW/COMM/KFAC/UPDATE, examples/pytorch_cifar10_resnet.py:289-339)
plus the --exclude-parts subtraction method (kfac_preconditioner_base.py:
96-99, consumed by scripts/parse_logs.py:44-73). Under jit the phases fuse
into one program, so the TPU equivalents are:

- :func:`trace` — a jax.profiler context writing an XLA trace (Perfetto /
  TensorBoard viewable) for true on-chip phase timing;
- :func:`exclude_parts_breakdown` — the subtraction method automated:
  time the jitted step once per ablation flag set and difference the
  means (this is the reference's attribution method, and it works under
  fusion because each ablation compiles to a smaller program).
"""

import contextlib
import time

import jax
import numpy as np

PHASES = ('ComputeFactor', 'CommunicateFactor', 'ComputeInverse',
          'CommunicateInverse')


@contextlib.contextmanager
def trace(log_dir):
    """jax.profiler trace context — the on-chip replacement for the manual
    phase timers."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def time_steps(step_fn, state, batch, iters=30, warmup=5, **kw):
    """Mean/std steady-state iteration time (the SPEED-mode measurement,
    reference :333-344)."""
    for _ in range(warmup):
        state, m = step_fn(state, batch, **kw)
    jax.block_until_ready(m)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, m = step_fn(state, batch, **kw)
        jax.block_until_ready(m)
        times.append(time.perf_counter() - t0)
    return float(np.mean(times)), float(np.std(times)), state


def exclude_parts_breakdown(make_step, batch, iters=20, **kw):
    """Attribute per-phase cost by ablation subtraction.

    ``make_step(exclude_parts) -> (step_fn, fresh_state)`` builds a step
    with the given phases excluded plus a matching fresh train state.
    Returns ``{phase: seconds}`` with 'Total' and the subtraction-derived
    per-phase costs (cumulative ablation, reference parse_logs.py:44-73).
    """
    results = {}
    excluded = []
    step, state = make_step('')
    t_full, _, _ = time_steps(step, state, batch, iters=iters, **kw)
    results['Total'] = t_full
    prev = t_full
    for phase in ('CommunicateInverse', 'ComputeInverse',
                  'CommunicateFactor', 'ComputeFactor'):
        excluded.append(phase)
        step, state = make_step(','.join(excluded))
        t, _, _ = time_steps(step, state, batch, iters=iters, **kw)
        results[phase] = max(prev - t, 0.0)
        prev = t
    results['Rest'] = prev
    return results
