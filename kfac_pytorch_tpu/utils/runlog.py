"""Per-run logging setup shared by the example trainers.

Reference convention: config-encoded log filenames (the reference bakes
model / kfac freq / world size / batch into its logfile names,
examples/pytorch_cifar10_resnet.py:318). Here each RUN additionally gets
its own file — the config-encoded stem plus a start-time suffix, opened
fresh ('w') — so reruns and A/B legs of the same config never append
into one ambiguous stream (``scripts/parse_logs.py`` treats each file as
one run and keys its tables off the filename).
"""

import logging
import os
import time


def setup_run_logging(log_dir, *parts, unique=True, process_id=None):
    """``basicConfig`` with stream + per-run file handler.

    ``parts`` are joined with '_' (None/empty dropped). Returns
    ``(logger, logfile_path)`` — the path is None on non-zero processes.

    Multi-process runs write the file from process 0 only (reference
    rank-0 logging convention, examples/pytorch_cifar10_resnet.py:145):
    on a shared filesystem the per-second timestamp suffix is identical
    across ranks, so peer FileHandlers opened with mode='w' would
    truncate each other. ``process_id`` defaults to the launcher-exported
    JAX_PROCESS_ID (launch_tpu.sh) — read from the environment rather
    than jax.process_index() so logging setup never triggers backend
    initialization.
    """
    if process_id is None:
        process_id = int(os.environ.get('JAX_PROCESS_ID', '0'))
    handlers = [logging.StreamHandler()]
    path = None
    if process_id == 0:
        os.makedirs(log_dir, exist_ok=True)
        stem = '_'.join(str(p) for p in parts if p not in (None, ''))
        if unique:
            stem += time.strftime('_%m%dT%H%M%S')
        path = os.path.join(log_dir, stem + '.log')
        handlers.append(logging.FileHandler(path, mode='w'))
    logging.basicConfig(
        level=logging.INFO, format='%(asctime)s %(message)s', force=True,
        handlers=handlers)
    return logging.getLogger(), path


def health_suffix(epoch_counts):
    """Format an epoch's health-guard deltas for the per-epoch log line.

    ``epoch_counts`` is ``metrics.HealthMonitor.epoch_flush()``'s dict.
    A clean epoch formats to '' so the common case stays the familiar
    reference-style line; an unhealthy one appends e.g.
    `` [health: skipped=2 sgd_fallbacks=1 max_rung=1]`` — grep run logs
    for ``[health:`` to find every epoch that hit the guard.
    """
    if not epoch_counts or not any(epoch_counts.values()):
        return ''
    return (' [health: skipped=%d sgd_fallbacks=%d max_rung=%d]'
            % (epoch_counts['skipped'], epoch_counts['fallbacks'],
               epoch_counts['max_rung']))
