"""Per-run logging setup shared by the example trainers.

Reference convention: config-encoded log filenames (the reference bakes
model / kfac freq / world size / batch into its logfile names,
examples/pytorch_cifar10_resnet.py:318). Here each RUN additionally gets
its own file — the config-encoded stem plus a start-time suffix, opened
fresh ('w') — so reruns and A/B legs of the same config never append
into one ambiguous stream (``scripts/parse_logs.py`` treats each file as
one run and keys its tables off the filename).
"""

import logging
import os
import time


def setup_run_logging(log_dir, *parts, unique=True):
    """``basicConfig`` with stream + per-run file handler.

    ``parts`` are joined with '_' (None/empty dropped). Returns
    ``(logger, logfile_path)``.
    """
    os.makedirs(log_dir, exist_ok=True)
    stem = '_'.join(str(p) for p in parts if p not in (None, ''))
    if unique:
        stem += time.strftime('_%m%dT%H%M%S')
    path = os.path.join(log_dir, stem + '.log')
    logging.basicConfig(
        level=logging.INFO, format='%(asctime)s %(message)s', force=True,
        handlers=[logging.StreamHandler(),
                  logging.FileHandler(path, mode='w')])
    return logging.getLogger(), path
