"""Per-run logging setup shared by the example trainers.

Reference convention: config-encoded log filenames (the reference bakes
model / kfac freq / world size / batch into its logfile names,
examples/pytorch_cifar10_resnet.py:318). Here each RUN additionally gets
its own file — the config-encoded stem plus a start-time suffix, opened
fresh ('w') — so reruns and A/B legs of the same config never append
into one ambiguous stream (``scripts/parse_logs.py`` treats each file as
one run and keys its tables off the filename).
"""

import atexit
import logging
import os
import signal
import time

_FLUSH_HOOKS_INSTALLED = False
_PREV_SIGTERM = None
_EXTRA_FLUSHERS = []


def register_flusher(fn):
    """Add a callback to the run-log flush chain (idempotent).

    Everything registered here runs wherever the log handlers flush:
    the atexit hook, the SIGTERM handler, the watchdog's pre-abort
    flush, and any direct :func:`flush_all_handlers` call. The trace
    ring buffer (``obs.trace``) rides this chain so a crash or
    preemption loses neither the log tail nor the trace tail."""
    if fn not in _EXTRA_FLUSHERS:
        _EXTRA_FLUSHERS.append(fn)


def unregister_flusher(fn):
    """Remove a callback added by :func:`register_flusher`."""
    if fn in _EXTRA_FLUSHERS:
        _EXTRA_FLUSHERS.remove(fn)


def flush_all_handlers():
    """Flush every root-logger handler and every registered extra
    flusher (best-effort)."""
    # extra flushers first: the trace buffer may want to LOG that it
    # dropped events, and the handler flush below must carry that line
    for fn in list(_EXTRA_FLUSHERS):
        try:
            fn()
        except Exception:  # noqa: BLE001 — flushing is best-effort
            pass
    for h in logging.getLogger().handlers:
        try:
            h.flush()
        except Exception:  # noqa: BLE001 — flushing is best-effort
            pass


def _sigterm_flush(signum, frame):
    """Flush the run log, then get out of the signal's way.

    Chain-aware: when a PreemptionGuard (or anything else) installed its
    handler OVER this one and is calling us as its chained predecessor,
    we only flush — the cooperative shutdown above us owns the exit.
    When WE are still the installed handler (no guard), flushing and
    returning would silently neuter SIGTERM, so restore whatever was
    here before us and re-deliver the signal — the process dies exactly
    as it would have, minus the lost log tail.
    """
    flush_all_handlers()
    if signal.getsignal(signum) is _sigterm_flush:
        prev = _PREV_SIGTERM
        signal.signal(signum,
                      prev if callable(prev) or prev in (
                          signal.SIG_IGN,) else signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_flush_hooks():
    """Idempotent: atexit + SIGTERM flush of the run-log handlers, so a
    crash, preemption or watchdog abort cannot lose the tail of the run
    log the supervisor needs for diagnosis. Called by
    :func:`setup_run_logging`; safe to call directly from bespoke
    trainers."""
    global _FLUSH_HOOKS_INSTALLED, _PREV_SIGTERM
    if _FLUSH_HOOKS_INSTALLED:
        return
    _FLUSH_HOOKS_INSTALLED = True
    atexit.register(flush_all_handlers)
    try:
        _PREV_SIGTERM = signal.signal(signal.SIGTERM, _sigterm_flush)
    except ValueError:  # pragma: no cover — non-main thread: atexit only
        pass


def uninstall_flush_hooks():
    """Undo :func:`install_flush_hooks` (test isolation)."""
    global _FLUSH_HOOKS_INSTALLED, _PREV_SIGTERM
    if not _FLUSH_HOOKS_INSTALLED:
        return
    _FLUSH_HOOKS_INSTALLED = False
    atexit.unregister(flush_all_handlers)
    if signal.getsignal(signal.SIGTERM) is _sigterm_flush:
        signal.signal(signal.SIGTERM,
                      _PREV_SIGTERM if _PREV_SIGTERM is not None
                      else signal.SIG_DFL)
    _PREV_SIGTERM = None


def setup_run_logging(log_dir, *parts, unique=True, process_id=None):
    """``basicConfig`` with stream + per-run file handler.

    ``parts`` are joined with '_' (None/empty dropped). Returns
    ``(logger, logfile_path)`` — the path is None on non-zero processes.

    Multi-process runs write the file from process 0 only (reference
    rank-0 logging convention, examples/pytorch_cifar10_resnet.py:145):
    on a shared filesystem the per-second timestamp suffix is identical
    across ranks, so peer FileHandlers opened with mode='w' would
    truncate each other. ``process_id`` defaults to the launcher-exported
    JAX_PROCESS_ID (launch_tpu.sh) — read from the environment rather
    than jax.process_index() so logging setup never triggers backend
    initialization.
    """
    if process_id is None:
        process_id = int(os.environ.get('JAX_PROCESS_ID', '0'))
    handlers = [logging.StreamHandler()]
    path = None
    if process_id == 0:
        os.makedirs(log_dir, exist_ok=True)
        stem = '_'.join(str(p) for p in parts if p not in (None, ''))
        if unique:
            stem += time.strftime('_%m%dT%H%M%S')
        path = os.path.join(log_dir, stem + '.log')
        handlers.append(logging.FileHandler(path, mode='w'))
    logging.basicConfig(
        level=logging.INFO, format='%(asctime)s %(message)s', force=True,
        handlers=handlers)
    # a crash/preemption/watchdog abort must not lose the log tail the
    # supervisor diagnoses from
    install_flush_hooks()
    return logging.getLogger(), path


def health_suffix(epoch_counts):
    """Format an epoch's health-guard deltas for the per-epoch log line.

    ``epoch_counts`` is ``metrics.HealthMonitor.epoch_flush()``'s dict.
    A clean epoch formats to '' so the common case stays the familiar
    reference-style line; an unhealthy one appends e.g.
    `` [health: skipped=2 sgd_fallbacks=1 max_rung=1]`` — grep run logs
    for ``[health:`` to find every epoch that hit the guard.
    """
    if not epoch_counts or not any(epoch_counts.values()):
        return ''
    return (' [health: skipped=%d sgd_fallbacks=%d max_rung=%d]'
            % (epoch_counts['skipped'], epoch_counts['fallbacks'],
               epoch_counts['max_rung']))


def kfac_phase_suffix(phase_ms):
    """Format per-phase K-FAC step timing for the epoch line.

    ``phase_ms`` is ``metrics.PhaseTimers.epoch_flush()``'s dict
    (stats/decomp/gather/pred marginals in ms, plus step_mean/step_max).
    Empty input formats to '' (no timers wired / nothing recorded);
    otherwise e.g. `` kfac_phase_ms=decomp+gather:3.1,pred:1.2,``
    ``stats:0.4,step_max:6.0,step_mean:4.8`` — grep run logs for
    ``kfac_phase_ms=`` to track where step time goes; the staggered
    refresh's win shows as step_max collapsing onto step_mean (no more
    periodic decomposition spike).
    """
    if not phase_ms:
        return ''
    body = ','.join(f'{k}:{v:.2f}' for k, v in sorted(phase_ms.items()))
    return f' kfac_phase_ms={body}'


def counter_deltas(now, prev):
    """Per-epoch view of cumulative resilience counters: ``now - prev``
    per key, except ``*_level`` keys which are gauges (current ladder
    position, not an event count) and pass through. Feed consecutive
    ``resilience.counters.snapshot()``s (plus ``governor.counts()``) so
    each epoch line reports what happened THAT epoch — matching
    ``health_suffix``'s per-epoch-delta semantics on the same line."""
    return {k: (v if k.endswith('_level') else v - prev.get(k, 0))
            for k, v in now.items()}


_RES_SUFFIX = None  # compiled lazily; runlog stays import-light


def parse_resilience_suffix(line):
    """Inverse of :func:`resilience_suffix`: extract the ``{name: value}``
    dict from a log line's ``[resilience: k=v ...]`` suffix, or {} when
    the line has none. Values parse to int when they look like ints,
    float otherwise, raw string as the fallback — the incident scraper
    (``resilience.incident``) is the consumer, so the parser accepts
    exactly what the formatter below emits plus numeric extras like
    ``detect_s=1.25``."""
    global _RES_SUFFIX
    if _RES_SUFFIX is None:
        import re
        _RES_SUFFIX = re.compile(r'\[resilience: ([^\]]+)\]')
    m = _RES_SUFFIX.search(line)
    if not m:
        return {}
    out = {}
    for part in m.group(1).split():
        if '=' not in part:
            continue
        k, v = part.split('=', 1)
        try:
            out[k] = int(v)
        except ValueError:
            try:
                out[k] = float(v)
            except ValueError:
                out[k] = v
    return out


def resilience_suffix(counts):
    """Format process-resilience counters for a log line.

    ``counts`` is any {name: int} dict — per-epoch deltas from
    :func:`counter_deltas` (what the example trainers log), a
    supervisor's cumulative ``counts()``, or their union. All-zero (the
    healthy common case) formats to '' so clean runs keep the familiar
    line; otherwise e.g. `` [resilience: io_retries=2
    watchdog_trips=1]`` — grep run logs for ``[resilience:`` to find
    every epoch (and every supervisor event) where the process layer
    had to act.
    """
    if not counts or not any(counts.values()):
        return ''
    body = ' '.join(f'{k}={v}' for k, v in sorted(counts.items()) if v)
    return f' [resilience: {body}]'
