"""Learning-rate schedules used by the reference harness.

Parity: warmup + multiplicative multi-step decay
(reference: examples/utils.py:54-66), polynomial decay (:68-80), and the
Transformer inverse-sqrt warmup (examples/transformer/Optim.py:40-63).
Step-indexed callables, traceable under jit (optax evaluates them on the
traced step counter), so they are written with jnp ops, no Python
branching.
"""

import jax.numpy as jnp
import numpy as np


def warmup_multistep(base_lr, steps_per_epoch, warmup_epochs, decay_epochs,
                     decay_factor=0.1, init_scale=None, scale=1.0):
    """Linear warmup from ``base_lr*init_scale`` to ``base_lr*scale`` over
    ``warmup_epochs``, then multiply by ``decay_factor`` at each epoch in
    ``decay_epochs``. ``scale`` is the large-batch multiplier (the
    reference scales base lr by world size,
    examples/pytorch_imagenet_resnet.py:219-231)."""
    if init_scale is None:
        init_scale = 1.0 / max(scale, 1.0)
    boundaries = jnp.asarray(sorted(decay_epochs or []), jnp.float32)

    def schedule(step):
        epoch = jnp.asarray(step, jnp.float32) / steps_per_epoch
        warm_frac = epoch / max(warmup_epochs, 1e-9)
        warm = base_lr * (init_scale + (scale - init_scale)
                          * jnp.minimum(warm_frac, 1.0))
        k = jnp.sum(epoch >= boundaries) if boundaries.size else 0
        decayed = base_lr * scale * (decay_factor ** k)
        if warmup_epochs:
            return jnp.where(epoch < warmup_epochs, warm, decayed)
        return decayed

    return schedule


def polynomial_decay(base_lr, total_steps, power=2.0, warmup_steps=0,
                     scale=1.0):
    """Polynomial decay to zero (reference: examples/utils.py:68-80)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base_lr * scale * step / max(warmup_steps, 1)
        t = jnp.clip(step - warmup_steps, 0, total_steps - warmup_steps)
        frac = 1.0 - t / max(total_steps - warmup_steps, 1)
        decayed = base_lr * scale * (frac ** power)
        return jnp.where(step < warmup_steps, warm, decayed)

    return schedule


def inverse_sqrt(d_model, warmup_steps=4000, lr_mul=1.0):
    """Transformer schedule: ``lr_mul * d^-0.5 * min(s^-0.5, s*w^-1.5)``
    (reference: examples/transformer/Optim.py:40-63)."""

    def schedule(step):
        s = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        return lr_mul * (d_model ** -0.5) * jnp.minimum(
            s ** -0.5, s * warmup_steps ** -1.5)

    return schedule
