"""Training metrics.

Parity: the distributed ``Metric`` accumulator and ``accuracy``
(reference: examples/utils.py:6-9, 39-52). The reference allreduce-averages
each update across ranks; here values produced by a jitted/shard_map step
are already replicated, so the accumulator is a plain weighted host
average — the collective happened on-device.
"""

import jax.numpy as jnp
import numpy as np


class Metric:
    """Weighted running average of scalars (loss, accuracy)."""

    def __init__(self, name):
        self.name = name
        self.total = 0.0
        self.n = 0.0

    def update(self, val, n=1):
        self.total += float(val) * n
        self.n += n

    @property
    def avg(self):
        return self.total / max(self.n, 1e-12)

    def sync(self):
        """Cross-process allreduce of (total, n) — the reference's
        allreduce-averaged Metric semantics on a multi-host pod
        (examples/utils.py:39-52). No-op on one process."""
        import jax
        if jax.process_count() == 1:
            return self
        from jax.experimental import multihost_utils
        agg = multihost_utils.process_allgather(
            np.asarray([self.total, self.n], np.float64))
        self.total = float(agg[:, 0].sum())
        self.n = float(agg[:, 1].sum())
        return self


def accuracy(outputs, labels):
    """Top-1 accuracy from logits (reference: examples/utils.py:6-9)."""
    pred = jnp.argmax(outputs, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))


def topk_accuracy(outputs, labels, k=5):
    topk = jnp.argsort(outputs, axis=-1)[:, -k:]
    hit = (topk == labels[:, None]).any(axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
