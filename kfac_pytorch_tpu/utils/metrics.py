"""Training metrics.

Parity: the distributed ``Metric`` accumulator and ``accuracy``
(reference: examples/utils.py:6-9, 39-52). The reference allreduce-averages
each update across ranks; here values produced by a jitted/shard_map step
are already replicated, so the accumulator is a plain weighted host
average — the collective happened on-device.
"""

import logging

import jax.numpy as jnp
import numpy as np


class HealthMonitor:
    """Host-side consumer of the step metrics' ``health/*`` counters
    (beyond reference — the in-jit guard lives in health.py).

    The jitted step returns CUMULATIVE on-device counters (total skipped
    batches, total raw-SGD fallbacks, current ladder rung); the monitor
    diffs them between ``update`` calls and logs a WARNING the moment
    something happens — a skipped batch, a ladder escalation, the
    degraded-SGD mode engaging, recovery — so run logs carry the event at
    the step it occurred, not just the end-of-run totals. ``epoch_flush``
    returns (and resets) per-epoch deltas for the epoch summary line
    (runlog.health_suffix formats them).

    Reading the counters costs no extra device sync in practice: the
    trainers already block on ``float(metrics['loss'])`` every step, so
    the health scalars ride along with an already-materialized result.
    """

    def __init__(self, log=None, state=None, registry=None):
        """``state``: pass the (possibly restored) TrainState so the
        baseline starts from ITS cumulative counters — without it, a
        resumed run's first update would re-announce every pre-resume
        skip as if it just happened.

        ``registry``: an ``obs.metrics.Registry`` — the monitor then
        publishes ``health/skipped``, ``health/fallbacks`` (counters)
        and ``health/max_rung`` (per-epoch watermark) so the registry's
        ``epoch_suffixes()`` renders the same ``[health: ...]`` suffix
        this class used to feed by hand (and exporters see the
        cumulative counts). The restored baseline is rebased so a
        resume's first epoch line reports only post-resume events —
        identical to the legacy ``epoch_flush`` semantics."""
        self.log = log if log is not None else logging.getLogger(__name__)
        self.skipped = 0      # cumulative, mirrors the device counter
        self.fallbacks = 0
        self.rung = 0
        h = getattr(state, 'health', None)
        if h is not None:
            self.skipped = int(h.skipped)
            self.fallbacks = int(h.fallbacks)
            self.rung = int(h.rung)
        self._epoch = {'skipped': 0, 'fallbacks': 0, 'max_rung': 0}
        self.registry = registry
        if registry is not None:
            registry.counter('health/skipped').rebase(self.skipped)
            registry.counter('health/fallbacks').rebase(self.fallbacks)
            registry.watermark('health/max_rung')

    def update(self, metrics, step=None):
        """Consume one step's metrics dict; no-op without health/*."""
        if 'health/skipped' not in metrics:
            return
        at = '' if step is None else f' at step {step}'
        skipped = int(metrics['health/skipped'])
        fallbacks = int(metrics['health/fallbacks'])
        rung = int(metrics['health/rung'])
        if skipped > self.skipped:
            self._epoch['skipped'] += skipped - self.skipped
            self.log.warning(
                'health: non-finite batch skipped%s (total %d) — params '
                'and factor EMAs untouched', at, skipped)
        if fallbacks > self.fallbacks:
            self._epoch['fallbacks'] += fallbacks - self.fallbacks
            self.log.warning(
                'health: non-finite preconditioner output%s — raw-SGD '
                'gradients used for this step (total %d)', at, fallbacks)
        if rung > self.rung:
            self.log.warning(
                'health: damping-escalation ladder climbed to rung %d%s',
                rung, at)
        elif rung < self.rung:
            self.log.info(
                'health: recovered%s — damping ladder reset to rung %d',
                at, rung)
        self._epoch['max_rung'] = max(self._epoch['max_rung'], rung)
        if self.registry is not None:
            self.registry.counter('health/skipped').set_total(skipped)
            self.registry.counter('health/fallbacks').set_total(fallbacks)
            self.registry.watermark('health/max_rung').set(rung)
        self.skipped, self.fallbacks, self.rung = skipped, fallbacks, rung

    def quality_signal(self):
        """Monotone badness counter for the autotuner's numerical-
        health gate (``KnobController(quality_gate=...)``): total
        skipped batches + raw-SGD fallbacks. A knob probe window that
        raised this number regressed accuracy and never commits,
        whatever its step time said."""
        return self.skipped + self.fallbacks

    def epoch_flush(self):
        """Per-epoch deltas ``{skipped, fallbacks, max_rung}``; resets the
        epoch accumulators (cumulative totals keep running)."""
        out, self._epoch = self._epoch, {'skipped': 0, 'fallbacks': 0,
                                         'max_rung': 0}
        return out


class PhaseTimers:
    """Host-side per-step wall-time attribution by K-FAC phase set
    (beyond reference — the staggered-refresh observability companion).

    Under jit every K-FAC phase fuses into one program, so per-phase
    time cannot be read off the device per step; what the host CAN see
    is which phases each dispatched variant ran
    (``step_fn.last_phases``: 'pred'/'stats'/'decomp'/'gather') and the
    step's wall time. The timers bucket wall times by phase set and at
    ``epoch_flush`` derive marginal per-phase costs by subtraction
    between observed sets — the passive, in-run form of the
    exclude-parts ablation method (utils/profiling.
    exclude_parts_breakdown). A set with no observed strict subset
    reports its joint mean under a '+'-joined label (e.g. a staggered
    fac-freq-1 run, where every step runs everything, honestly reports
    one ``decomp+gather+pred+stats`` figure).

    ``step_max``/``step_mean`` always ride along: the refresh spike —
    and its removal under ``stagger=True`` — is visible as
    ``step_max/step_mean`` collapsing toward 1 in the epoch lines
    (runlog.kfac_phase_suffix formats the dict).
    """

    def __init__(self, tracer=None, registry=None, histogram=False):
        """``tracer``: an ``obs.trace.TraceRecorder`` — every recorded
        step then ALSO lands as a Chrome-trace span named
        ``kfac.step``, carrying the step's phase set in the
        exclude-parts ledger taxonomy (``obs.trace.PHASE_TAXONOMY``), so
        the same host-side attribution this class aggregates is
        inspectable step-by-step in Perfetto.

        ``registry``: an ``obs.metrics.Registry`` — ``collect`` (or a
        direct ``epoch_flush``-then-set) publishes the per-epoch phase
        marginals as ``kfac_phase/*`` epoch gauges, which the registry
        renders into the exact legacy ``kfac_phase_ms=`` suffix.
        ``histogram=True`` additionally feeds a ``step_seconds``
        histogram (Prometheus-shaped step-time distribution)."""
        self._acc = {}
        self._max = 0.0
        self._total = 0.0
        self._n = 0
        self.tracer = tracer
        self.registry = registry
        self._histogram = histogram
        if registry is not None:
            registry.add_collector(self.collect)
            if histogram:
                registry.histogram('step_seconds')

    def record(self, phases, seconds):
        """One step's wall time, attributed to its phase set. Call with
        the COMPLETED step's duration (time around the dispatch plus the
        blocking metric read that materializes it)."""
        key = frozenset(phases)
        tot, n = self._acc.get(key, (0.0, 0))
        self._acc[key] = (tot + seconds, n + 1)
        self._total += seconds
        self._n += 1
        self._max = max(self._max, seconds)
        if self.tracer is not None:
            from kfac_pytorch_tpu.obs.trace import taxonomy_phases
            self.tracer.complete('kfac.step', seconds, cat='kfac.step',
                                 phases=taxonomy_phases(phases))
        if self.registry is not None and self._histogram:
            self.registry.histogram('step_seconds').observe(seconds)

    def collect(self, registry):
        """Registry collector: flush the epoch's marginals into
        ``kfac_phase/<label>`` epoch gauges (reset after each flush so a
        phase set that disappears — a variant change, an idle epoch —
        cannot leak a stale number into the next epoch line)."""
        for label, ms in self.epoch_flush().items():
            registry.gauge('kfac_phase/' + label,
                           reset_on_flush=True).set(ms)

    def epoch_flush(self):
        """Per-epoch ``{label: ms}`` (resets the accumulators): marginal
        per-phase costs where a baseline set was observed, joint means
        otherwise, plus ``step_mean``/``step_max``. Empty dict when
        nothing was recorded."""
        means = {k: t / n for k, (t, n) in self._acc.items()}
        out = {}
        for s in sorted(means, key=lambda k: (len(k), sorted(k))):
            bases = [b for b in means if b < s]
            if bases:
                # deterministic base pick; and the FIRST derivation of a
                # label wins — smaller sets are flushed first and their
                # baselines are the better-sampled ones (a refresh step's
                # 'stats' marginal would be the noisiest estimate)
                base = max(bases, key=lambda b: (len(b), tuple(sorted(b))))
                label = '+'.join(sorted(s - base))
                val = max(means[s] - means[base], 0.0)
            else:
                label = '+'.join(sorted(s)) if s else 'step'
                val = means[s]
            if label and label not in out:
                out[label] = val
        if self._n:
            out['step_mean'] = self._total / self._n
            out['step_max'] = self._max
        self._acc, self._max, self._total, self._n = {}, 0.0, 0.0, 0
        return {k: v * 1000.0 for k, v in out.items()}


class Metric:
    """Weighted running average of scalars (loss, accuracy)."""

    def __init__(self, name):
        self.name = name
        self.total = 0.0
        self.n = 0.0

    def update(self, val, n=1):
        self.total += float(val) * n
        self.n += n

    @property
    def avg(self):
        return self.total / max(self.n, 1e-12)

    def sync(self):
        """Cross-process allreduce of (total, n) — the reference's
        allreduce-averaged Metric semantics on a multi-host pod
        (examples/utils.py:39-52). No-op on one process."""
        import jax
        if jax.process_count() == 1:
            return self
        from jax.experimental import multihost_utils
        agg = multihost_utils.process_allgather(
            np.asarray([self.total, self.n], np.float64))
        self.total = float(agg[:, 0].sum())
        self.n = float(agg[:, 1].sum())
        return self


def accuracy(outputs, labels):
    """Top-1 accuracy from logits (reference: examples/utils.py:6-9)."""
    pred = jnp.argmax(outputs, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))


def topk_accuracy(outputs, labels, k=5):
    topk = jnp.argsort(outputs, axis=-1)[:, -k:]
    hit = (topk == labels[:, None]).any(axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
