"""Loss helpers.

Parity: ``LabelSmoothLoss`` (reference: examples/utils.py:20-32) and the
pseudo-label sampler used for true-Fisher Monte-Carlo factor estimation
(reference: examples/utils.py:83-90).
"""

import jax
import jax.numpy as jnp


def label_smoothing_cross_entropy(outputs, labels, smoothing=0.1,
                                  num_classes=None):
    """CE against a smoothed one-hot target (reference:
    examples/utils.py:20-32)."""
    if num_classes is None:
        num_classes = outputs.shape[-1]
    logp = jax.nn.log_softmax(outputs, axis=-1)
    onehot = jax.nn.one_hot(labels, num_classes)
    target = onehot * (1.0 - smoothing) + smoothing / num_classes
    return -(target * logp).sum(axis=-1).mean()


def sample_pseudo_labels(rng, outputs):
    """Sample labels from the model's predictive distribution — the
    true-Fisher MC estimator's backward targets (reference:
    examples/utils.py:83-90)."""
    return jax.random.categorical(rng, outputs, axis=-1)
