"""Harness utilities — parity with the reference's examples/utils.py."""

from kfac_pytorch_tpu.utils.metrics import (
    Metric, HealthMonitor, PhaseTimers, accuracy)
from kfac_pytorch_tpu.utils.lr import (
    warmup_multistep, polynomial_decay, inverse_sqrt)
from kfac_pytorch_tpu.utils.losses import (
    label_smoothing_cross_entropy, sample_pseudo_labels)
from kfac_pytorch_tpu.utils.checkpoint import (
    save_checkpoint, restore_checkpoint, find_resume_epoch, auto_resume,
    PreemptionGuard, StaleLineageError, wait_for_checkpoints,
    prune_checkpoints, reshard_kfac_state, write_world_stamp,
    read_world_stamp, read_world_stamp_info)
from kfac_pytorch_tpu.utils.profiling import (
    trace, time_steps, exclude_parts_breakdown)

__all__ = [
    'Metric', 'HealthMonitor', 'PhaseTimers', 'accuracy', 'warmup_multistep',
    'polynomial_decay',
    'inverse_sqrt', 'label_smoothing_cross_entropy', 'sample_pseudo_labels',
    'save_checkpoint', 'restore_checkpoint', 'find_resume_epoch',
    'auto_resume',
    'PreemptionGuard', 'StaleLineageError', 'wait_for_checkpoints',
    'prune_checkpoints',
    'reshard_kfac_state', 'write_world_stamp', 'read_world_stamp',
    'read_world_stamp_info',
    'trace', 'time_steps', 'exclude_parts_breakdown',
]
