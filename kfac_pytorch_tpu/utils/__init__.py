"""Harness utilities — parity with the reference's examples/utils.py.

The metrics/lr/losses/checkpoint/profiling surface needs jax; runlog
(which the resilience plane lazy-imports from inside protocol code)
does not. In a jax-less environment (the CI fleet-sim/lint lanes, a
bare coordination host) only the jax-free part of this package loads —
same convention as the top-level ``kfac_pytorch_tpu/__init__.py``.
"""

try:
    from kfac_pytorch_tpu.utils.metrics import (
        Metric, HealthMonitor, PhaseTimers, accuracy)
    from kfac_pytorch_tpu.utils.lr import (
        warmup_multistep, polynomial_decay, inverse_sqrt)
    from kfac_pytorch_tpu.utils.losses import (
        label_smoothing_cross_entropy, sample_pseudo_labels)
    from kfac_pytorch_tpu.utils.checkpoint import (
        save_checkpoint, restore_checkpoint, find_resume_epoch,
        auto_resume, PreemptionGuard, StaleLineageError,
        wait_for_checkpoints, prune_checkpoints, reshard_kfac_state,
        write_world_stamp, read_world_stamp, read_world_stamp_info)
    from kfac_pytorch_tpu.utils.profiling import (
        trace, time_steps, exclude_parts_breakdown)
except ModuleNotFoundError as _e:  # pragma: no cover - jax-less lanes
    if _e.name not in ('jax', 'jaxlib'):
        raise

__all__ = [
    'Metric', 'HealthMonitor', 'PhaseTimers', 'accuracy', 'warmup_multistep',
    'polynomial_decay',
    'inverse_sqrt', 'label_smoothing_cross_entropy', 'sample_pseudo_labels',
    'save_checkpoint', 'restore_checkpoint', 'find_resume_epoch',
    'auto_resume',
    'PreemptionGuard', 'StaleLineageError', 'wait_for_checkpoints',
    'prune_checkpoints',
    'reshard_kfac_state', 'write_world_stamp', 'read_world_stamp',
    'read_world_stamp_info',
    'trace', 'time_steps', 'exclude_parts_breakdown',
]
