"""Dependency-free TensorBoard scalar export.

The reference optionally wires torch's SummaryWriter (and in fact ships
with it disabled: examples/pytorch_imagenet_resnet.py:169-178 sets
``log_writer = None``); here scalar export is first-class and native —
event files are written directly in the TFRecord + Event-proto wire
format (hand-encoded; no torch/tensorboard import in the hot path), so
the framework needs no logging dependency and the files load in stock
TensorBoard.

Wire format notes (both are stable public formats):
  record  = len(u64 LE) | masked_crc32c(len) | payload | masked_crc32c(payload)
  Event   = 1: wall_time (double) | 2: step (varint int64)
          | 3: file_version (string, first record only) | 5: Summary
  Summary = 1: repeated Value;  Value = 1: tag (string) | 2: simple_value
"""

import os
import socket
import struct
import time


def _crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _crc32c_table()


def _crc32c(data):
    c = 0xFFFFFFFF
    for b in data:
        c = _TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data):
    c = _crc32c(data)
    return ((((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field(num, wire, payload):
    return _varint((num << 3) | wire) + payload


def _len_delim(num, payload):
    return _field(num, 2, _varint(len(payload)) + payload)


def _event(wall_time, step=None, file_version=None, tag=None, value=None):
    msg = _field(1, 1, struct.pack('<d', wall_time))
    if step is not None:
        msg += _field(2, 0, _varint(step))
    if file_version is not None:
        msg += _len_delim(3, file_version.encode())
    if tag is not None:
        val = _len_delim(1, tag.encode()) + _field(
            2, 5, struct.pack('<f', float(value)))
        msg += _len_delim(5, _len_delim(1, val))
    return msg


class SummaryWriter:
    """Minimal scalar-only TensorBoard writer.

    Usage mirrors the torch API surface the reference gates on
    (add_scalar/flush/close); construct on rank 0 only, like the
    reference's first-worker gating."""

    def __init__(self, log_dir):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f'events.out.tfevents.{int(time.time())}.'
                 f'{socket.gethostname()}.{os.getpid()}')
        self._f = open(os.path.join(log_dir, fname), 'wb')
        self._write(_event(time.time(), file_version='brain.Event:2'))

    def _write(self, payload):
        header = struct.pack('<Q', len(payload))
        self._f.write(header + struct.pack('<I', _masked_crc(header))
                      + payload + struct.pack('<I', _masked_crc(payload)))

    def add_scalar(self, tag, value, step):
        self._write(_event(time.time(), step=int(step), tag=tag,
                           value=value))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


def maybe_writer(tb_dir):
    """Rank-0-gated writer (the reference's first-worker gating)."""
    import jax
    if tb_dir and jax.process_index() == 0:
        return SummaryWriter(tb_dir)
    return None


def _read_varint(buf, i):
    n = shift = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _walk_fields(buf):
    """Yield (field_number, wire_type, value) over one proto message.
    value is: varint int (wire 0), 8-byte bytes (wire 1), payload bytes
    (wire 2), 4-byte bytes (wire 5)."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 0x7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v, i = buf[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wire == 5:
            v, i = buf[i:i + 4], i + 4
        else:  # pragma: no cover — the writer never emits groups
            raise ValueError(f'unsupported wire type {wire}')
        yield num, wire, v


def read_scalars(log_dir):
    """Read every scalar series from the event files under ``log_dir`` —
    the inverse of :class:`SummaryWriter` (same hand-decoded TFRecord +
    Event wire format, so the round trip needs no tensorboard install;
    also loads files written by stock writers as long as they carry
    simple_value summaries). Returns ``{tag: [(step, value), ...]}``
    in file order; multiple event files are read in filename order."""
    series = {}
    names = sorted(f for f in os.listdir(log_dir)
                   if f.startswith('events.out.tfevents'))
    for name in names:
        with open(os.path.join(log_dir, name), 'rb') as f:
            data = f.read()
        i = 0
        while i + 12 <= len(data):
            (ln,) = struct.unpack('<Q', data[i:i + 8])
            if i + 12 + ln + 4 > len(data):
                break  # truncated tail (live writer / killed run) — skip
            payload = data[i + 12:i + 12 + ln]
            i += 12 + ln + 4  # len + len-crc + payload + payload-crc
            step = 0
            for num, wire, v in _walk_fields(payload):
                if num == 2 and wire == 0:
                    step = v
                elif num == 5 and wire == 2:      # Summary
                    for n2, w2, val_msg in _walk_fields(v):
                        if n2 != 1 or w2 != 2:
                            continue
                        tag, value = None, None
                        for n3, w3, v3 in _walk_fields(val_msg):
                            if n3 == 1 and w3 == 2:
                                tag = v3.decode()
                            elif n3 == 2 and w3 == 5:
                                (value,) = struct.unpack('<f', v3)
                        if tag is not None and value is not None:
                            series.setdefault(tag, []).append(
                                (step, value))
    return series


def log_epoch_scalars(tb, epoch, train_loss, lr, val_loss, val_acc):
    """The trainers' shared per-epoch scalar set. ``tb`` may be None.
    Callers must pass already-synced metric values — Metric.sync() is a
    cross-process collective and must run on every rank, never inside a
    rank-0-only branch."""
    if tb is None:
        return
    tb.add_scalar('train/loss', train_loss, epoch)
    tb.add_scalar('train/lr', lr, epoch)
    tb.add_scalar('val/loss', val_loss, epoch)
    tb.add_scalar('val/accuracy', val_acc, epoch)
    tb.flush()
