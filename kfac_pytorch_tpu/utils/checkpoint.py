"""Checkpoint / resume via orbax.

Parity and upgrade over the reference (examples/utils.py:11-18 rank-0
torch.save of {model, optimizer}; auto-resume by scanning
checkpoint-{epoch} downward, examples/pytorch_imagenet_resnet.py:162-167,
305-312). Upgrade: the K-FAC factor/decomposition state is checkpointed
too (the reference explicitly does NOT checkpoint m_A/m_G — factors
rebuild from running averages after resume; restoring them here makes
resume bit-faithful). Set ``include_kfac=False`` for reference-equivalent
behavior.
"""

import os
import re

import jax
import numpy as np

from kfac_pytorch_tpu import store as _store
from kfac_pytorch_tpu.store import manifest as _manifest

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _ckpt_dir(base, epoch):
    return os.path.join(os.path.abspath(base), f'checkpoint-{epoch}')


_ASYNC_CKPTR = None  # lazily-created persistent checkpointer (async saves)

#: (base_dir, epoch) of an async orbax save whose manifest commit is
#: deferred until the save is durable — the manifest IS the commit
#: point, so it may only ever be written after wait_until_finished
_PENDING_MANIFEST = None


class CheckpointCorruptError(OSError):
    """A restored blob failed its manifest hash/size check — silent
    storage corruption, not a transient read failure. ``auto_resume``
    treats it like any unreadable checkpoint: log and scan down."""


def _store_for(base_dir):
    """The object-store stack for a checkpoint namespace (posix by
    default — byte-compatible with the pre-store file layout;
    ``KFAC_STORE_BACKEND=http`` routes everything through the
    kfac-store-serve object server)."""
    return _store.store_from_env(os.path.abspath(str(base_dir)))


def _store_guard(fn):
    """Run one store operation; a spent retry budget means the
    durability plane is GONE — exit loudly with the dedicated rc
    rather than letting the trainer continue with nothing durable
    behind it (or mis-classify the failure as a corrupt checkpoint)."""
    try:
        return fn()
    except _store.StoreGiveUp as e:
        import logging
        logging.getLogger(__name__).error(
            'checkpoint store lost — %s; exiting rc=%d '
            '[resilience: store_lost=1]', e, _store.RC_STORE_LOST)
        raise SystemExit(_store.RC_STORE_LOST) from e


def _commit_manifest(base_dir, store, epoch, kind, blobs):
    """The atomic commit point: every blob is already durable, the
    manifest names them all (content hash + size each) and lands
    LAST with one atomic put. Lineage/gen/world provenance is copied
    from the ``world.json`` stamp written through the
    :func:`write_world_stamp` fence, so a fenced fork's manifest is
    refusable by the same monotonic-lineage rule."""
    stamp = read_world_stamp_info(base_dir)
    manifest = _manifest.build_manifest(epoch, kind, blobs, stamp=stamp)
    raw = _manifest.encode_manifest(manifest)
    _store_guard(
        lambda: store.put(_manifest.manifest_key(epoch), raw))
    import logging
    logging.getLogger(__name__).info(
        'ckpt: committed manifest epoch=%d blobs=%d kind=%s',
        int(epoch), len(manifest['blobs']), kind)


def _commit_manifest_tree(base_dir, epoch):
    """Hash (and, on a remote store, upload) a finished orbax
    checkpoint tree, then commit its manifest. Rank-0 only, called
    strictly AFTER the async writer reported the tree durable."""
    root = _ckpt_dir(base_dir, epoch)
    if not os.path.isdir(root):
        return
    store = _store_for(base_dir)
    local = _store.local_root(store) == os.path.abspath(str(base_dir))
    rel_root = f'checkpoint-{int(epoch)}'
    blobs = {}
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            with open(path, 'rb') as f:
                data = f.read()
            key = (rel_root + '/'
                   + os.path.relpath(path, root).replace(os.sep, '/'))
            if not local:
                _store_guard(
                    lambda key=key, data=data: store.put(key, data))
            blobs[key] = (_manifest.blob_sha256(data), len(data))
    _commit_manifest(base_dir, store, epoch, 'orbax', blobs)


def _flush_pending_manifest():
    global _PENDING_MANIFEST
    if _PENDING_MANIFEST is None:
        return
    base_dir, epoch = _PENDING_MANIFEST
    _PENDING_MANIFEST = None
    _commit_manifest_tree(base_dir, epoch)


def save_checkpoint(base_dir, epoch, state, include_kfac=True, block=True,
                    retry=None):
    """Write one checkpoint (one copy on disk — the reference's rank-0
    torch.save semantics, examples/utils.py:11-18).

    ``block=False`` returns as soon as the on-device state is snapshotted
    and lets orbax write to disk in the background — the save hides
    behind the next epoch's compute (beyond reference, which blocks on
    torch.save). Call :func:`wait_for_checkpoints` before process exit
    (and before acting on a just-saved preemption checkpoint).

    ``retry``: an optional ``resilience.RetryPolicy`` — a transient
    write failure (flaky NFS/GCS mount returning EIO) is retried with
    backoff instead of ending the run. Safe to replay: the pickle path
    is atomic tmp+rename and orbax's ``force=True`` overwrites. A
    PERSISTENT failure still raises the underlying ``OSError`` once the
    policy is exhausted. Single-process/pickle only for now — under the
    orbax multi-process barrier a lone rank replaying the save would
    desynchronize the barrier, so multi-process runs should keep
    ``retry=None`` there.

    Multi-process note: on the orbax path EVERY process must call this —
    orbax's save opens with a global process barrier and coordinates who
    writes what (single-file rank-0 output is an orbax detail, not an
    early-return here; an early return would strand the other ranks in
    the barrier). The pickle fallback is genuinely rank-0-only.
    """
    if retry is not None:
        from kfac_pytorch_tpu.resilience.retry import call_with_retry
        return call_with_retry(
            lambda: _save_checkpoint_once(base_dir, epoch, state,
                                          include_kfac, block),
            policy=retry, label=f'save checkpoint-{epoch}')
    return _save_checkpoint_once(base_dir, epoch, state, include_kfac,
                                 block)


def _save_checkpoint_once(base_dir, epoch, state, include_kfac, block):
    payload = state
    if not include_kfac:
        payload = state.replace(kfac_state=None)
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX:
        from kfac_pytorch_tpu import faults as _faults
        fault = (_faults.checkpoint_fault_mode()
                 if jax.process_index() == 0 else None)
        if fault == 'eio_once':
            if _faults.claim_ckpt_eio_once():
                import errno
                import logging
                logging.getLogger(__name__).warning(
                    'CHAOS FAULT ACTIVE: %s=eio_once — failing this '
                    'checkpoint write once', _faults.ENV_CKPT)
                raise OSError(errno.EIO,
                              'injected transient checkpoint write '
                              f'failure ({_faults.ENV_CKPT}=eio_once)')
            fault = None
        if fault:
            import logging
            logging.getLogger(__name__).warning(
                'CHAOS FAULT ACTIVE: %s=%s — deliberately corrupting the '
                'checkpoint write for epoch %s', _faults.ENV_CKPT, fault,
                epoch)
        if jax.process_index() == 0:
            os.makedirs(base_dir, exist_ok=True)
        global _ASYNC_CKPTR, _PENDING_MANIFEST
        if _ASYNC_CKPTR is None:
            _ASYNC_CKPTR = ocp.StandardCheckpointer()
        else:
            # surface a PREVIOUS async save's failure here, attributed to
            # this call site's logs, rather than letting it abort an
            # unrelated later save (e.g. the preemption grace-window one)
            try:
                _ASYNC_CKPTR.wait_until_finished()
            except Exception:  # noqa: BLE001 — log and keep checkpointing
                import logging
                _PENDING_MANIFEST = None  # that save never became durable
                logging.getLogger(__name__).exception(
                    'a previous async checkpoint save failed; attempting '
                    'this save anyway')
                _ASYNC_CKPTR = ocp.StandardCheckpointer()
            else:
                _flush_pending_manifest()
        _ASYNC_CKPTR.save(path, payload, force=True)
        if block or fault:
            _ASYNC_CKPTR.wait_until_finished()
        if jax.process_index() != 0:
            return
        if fault == 'truncate':
            # chaos drill: silent storage corruption AFTER the tree
            # landed — one published file truncated in place, and no
            # manifest, so the resume scan refuses the epoch outright
            for dirpath, _dirs, files in sorted(os.walk(path)):
                for name in sorted(files):
                    target = os.path.join(dirpath, name)
                    size = os.path.getsize(target)
                    with open(target, 'r+b') as f:
                        f.truncate(max(1, size // 2))
                    return
            return
        if fault == 'fail':
            # the commit dies between the tree and its manifest — the
            # exact torn-commit window the manifest-last protocol makes
            # harmless (epoch uncommitted, scan-down resumes older)
            raise OSError('injected checkpoint write failure '
                          f'({_faults.ENV_CKPT}=fail)')
        if block:
            _commit_manifest_tree(base_dir, epoch)
        else:
            _PENDING_MANIFEST = (os.path.abspath(str(base_dir)),
                                 int(epoch))
    else:
        if jax.process_index() != 0:
            return
        os.makedirs(base_dir, exist_ok=True)
        import pickle

        from kfac_pytorch_tpu import faults as _faults
        blob = pickle.dumps(jax.tree.map(np.asarray, payload))
        key = f'checkpoint-{epoch}.pkl'
        fault = _faults.checkpoint_fault_mode()
        if fault == 'eio_once':
            # transient-storage drill: the FIRST write attempt dies with
            # EIO before touching disk; a retry policy turns this into a
            # logged hiccup, no policy into the crash it used to be
            if _faults.claim_ckpt_eio_once():
                import errno
                import logging
                logging.getLogger(__name__).warning(
                    'CHAOS FAULT ACTIVE: %s=eio_once — failing this '
                    'checkpoint write once', _faults.ENV_CKPT)
                raise OSError(errno.EIO,
                              'injected transient checkpoint write '
                              f'failure ({_faults.ENV_CKPT}=eio_once)')
            fault = None
        if fault:
            # loud by design: a drill env var leaking into a real run
            # must be visible in its logs, not discovered at next resume
            import logging
            logging.getLogger(__name__).warning(
                'CHAOS FAULT ACTIVE: %s=%s — deliberately corrupting the '
                'checkpoint write for epoch %s', _faults.ENV_CKPT, fault,
                epoch)
        store = _store_for(base_dir)
        if fault == 'truncate':
            # chaos drill: a torn object lands under the FINAL key with
            # no manifest — the manifest-aware resume scan refuses the
            # epoch without ever reading it (pre-manifest behavior was
            # to select it and crash into the truncation)
            _store_guard(lambda: store.put(
                key, blob[:max(1, len(blob) // 2)]))
            return
        if fault == 'fail':
            # the write dies mid-upload: a partial tmp file, never a
            # final object and never a manifest
            with open(path + '.pkl.tmp', 'wb') as f:
                f.write(blob[:max(1, len(blob) // 2)])
                f.flush()
            raise OSError('injected checkpoint write failure '
                          f'({_faults.ENV_CKPT}=fail)')
        # atomic put (posix: full write to a tmp name, fsync, rename) —
        # a crash at any point leaves either the old object or the new
        # one, never a truncated final object — then the manifest LAST:
        # the epoch is committed only once its content hash is recorded
        _store_guard(lambda: store.put(key, blob))
        _commit_manifest(base_dir, store, epoch, 'pickle', {key: blob})


def reshard_kfac_state(pre_old, pre_new, kfac_state, carry_decomp=False):
    """Elastic world-size resume (beyond the reference): re-lay the
    K-FAC FACTOR state from ``pre_old``'s plan (its ``num_devices``)
    into ``pre_new``'s — restore a checkpoint taken at one world size
    into a differently-sized mesh.

    The stacked-bucket layout is device-major per world size (plan.py),
    so a num_devices change reshuffles which row of which bucket holds
    each layer's factor — both plans' ``layer_rows`` maps make the
    transport exact, and in BOTH directions: shrinking packs the rows
    into fewer shards, growing spreads them over more (any pad rows the
    new, less-even layout needs start from the fresh zero init and are
    never read — pad-row-exact, pinned by the N->M->N roundtrip tests). Only the FACTORS (the accumulated statistics —
    the state that takes thousands of steps to rebuild) are carried by
    default; decompositions re-initialize to zero and are recomputed at
    the first inverse update, exactly the fresh-start degrade path the
    trainer already handles (training.py seen-inverse gating; E-KFAC
    scales likewise re-accumulate — they are basis-bound). The step
    counter is preserved.

    ``carry_decomp`` (ISSUE 14, the live-replanning transport): when
    both preconditioners decompose by the SAME method, also transport
    the stored decompositions through the identical per-layer row
    remap — each row's decomposition is a property of that row's
    (identity-padded) factor alone, so a FULL-row move is exact at any
    world size (true-block slicing would be wrong here: eigh orders
    eigenvalues globally, interleaving the pad block's unit eigenpairs
    with the true spectrum). The relaunched/replanned run then resumes
    *preconditioning* immediately instead of passing gradients through
    until the next inverse refresh — the shrink/grow relaunch critical
    path the replan routing cuts. New pad rows stay at the zero init
    (never read); E-KFAC scales stay transport-transient either way
    (their group layout is comm-mode bound, not row bound). Ignored
    when the methods differ (an eigen<->cholesky replan rebuilds the
    decomposition from the carried factors).

    Host-side numpy: call OUTSIDE jit, with the old state fully
    addressable (single-host restore, or after a replicated restore).
    Both preconditioners must be set up on the same layer list.
    """
    plan_o, plan_n = pre_old.plan, pre_new.plan
    assert plan_o is not None and plan_n is not None, 'call setup() first'
    sig_o = [(m.path, m.in_dim, m.out_dim) for m in plan_o.metas]
    sig_n = [(m.path, m.in_dim, m.out_dim) for m in plan_n.metas]
    assert sig_o == sig_n, (
        'elastic resume requires the same layer set (paths AND dims — a '
        f'width change invalidates the statistics): {sig_o} != {sig_n}')
    fresh = pre_new.init()
    factors = {k: np.array(v) for k, v in fresh.factors.items()}
    old = {k: np.asarray(v) for k, v in kfac_state.factors.items()}
    carry_decomp = (carry_decomp and pre_old.method == pre_new.method)
    decomp = None
    old_decomp = None
    if carry_decomp:
        # leaf groups that are per-row bucket stacks (scales are group-
        # keyed and comm-mode shaped — never row-transported)
        decomp = {grp: {k: np.array(v) for k, v in leaves.items()}
                  for grp, leaves in fresh.decomp.items()
                  if grp in ('evals', 'evecs', 'invs')}
        old_decomp = {grp: {k: np.asarray(v) for k, v in leaves.items()}
                      for grp, leaves in kfac_state.decomp.items()
                      if grp in decomp}
    for i, meta in enumerate(plan_o.metas):
        ba_o, ra_o, bg_o, rg_o, _ = plan_o.layer_rows[i]
        ba_n, ra_n, bg_n, rg_n, _ = plan_n.layer_rows[i]
        da, dg = meta.in_dim, meta.out_dim
        factors[str(ba_n)][ra_n, :da, :da] = old[str(ba_o)][ra_o, :da, :da]
        factors[str(bg_n)][rg_n, :dg, :dg] = old[str(bg_o)][rg_o, :dg, :dg]
        if carry_decomp:
            for grp in decomp:
                dst, src = decomp[grp], old_decomp[grp]
                dst[str(ba_n)][ra_n] = src[str(ba_o)][ra_o]
                dst[str(bg_n)][rg_n] = src[str(bg_o)][rg_o]
    import jax.numpy as jnp
    out = fresh.replace(
        step=jnp.asarray(np.asarray(kfac_state.step)),
        factors={k: jnp.asarray(v) for k, v in factors.items()})
    if carry_decomp:
        new_decomp = dict(out.decomp)
        for grp, leaves in decomp.items():
            new_decomp[grp] = {k: jnp.asarray(v) for k, v in leaves.items()}
        out = out.replace(decomp=new_decomp)
    return out


class StaleLineageError(RuntimeError):
    """This process belongs to an abandoned (fenced) fork of the pod:
    the on-disk ``world.json`` records a NEWER lineage epoch than the
    one this process was launched with. Resuming — or re-stamping —
    would clobber the surviving lineage's state, so both refuse."""


def write_world_stamp(base_dir, num_devices, gen=None, lineage=None):
    """Record the K-FAC world size the checkpoints in ``base_dir`` were
    taken at (``world.json``, atomic, rank-0 only). The elastic resume
    path (``resilience.elastic.elastic_resume``) compares this stamp to
    the relaunched trainer's world and routes a mismatch — in EITHER
    direction: a shrunken pod reshards down, a re-grown one reshards up
    — through :func:`reshard_kfac_state`; without the stamp the relaunch
    would try to restore factor buckets shaped for the old mesh and die
    on a structure mismatch. ``gen`` (optional) records the pod
    generation the stamp was written under (``KFAC_POD_GEN`` from the
    pod supervisor) — provenance for churn forensics, not protocol
    state.

    ``lineage`` (optional, ``KFAC_LINEAGE`` from the pod supervisor) is
    PROTOCOL state: the monotonic lineage epoch of the membership this
    trainer belongs to. The stamp may never move backward — a writer at
    a LOWER lineage than the one on disk is a fenced fork's straggler,
    and overwriting here would be exactly the split-brain clobber the
    quorum gate exists to prevent: it raises :class:`StaleLineageError`
    instead (commit fencing's last line of defense; the first is that a
    fenced supervisor never relaunches its trainer at all)."""
    if jax.process_index() != 0:
        return
    from kfac_pytorch_tpu.resilience import atomic_write_json
    os.makedirs(base_dir, exist_ok=True)
    stamp = {'num_devices': int(num_devices)}
    if gen is not None:
        stamp['gen'] = int(gen)
    target = os.path.join(os.path.abspath(base_dir), 'world.json')
    if lineage is None:
        atomic_write_json(target, stamp)
        return
    # check-then-write must be atomic against a CONCURRENT higher-
    # lineage writer (the race: a fenced straggler reads the old stamp,
    # the majority writes the new one, the straggler's replace moves it
    # backward) — serialize through an advisory lock next to the stamp.
    # Best-effort: on filesystems without flock semantics (gcsfuse) the
    # check still runs unserialized, and the OTHER two fencing layers
    # (the fenced supervisor killing its trainer; elastic_resume
    # refusing a newer-lineage stamp) carry the guarantee.
    import contextlib
    lock_cm = contextlib.nullcontext()
    try:
        import fcntl
        lock_f = open(target + '.lock', 'w')
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        lock_cm = lock_f  # closing releases the lock
    except (ImportError, OSError):
        pass
    with lock_cm:
        existing = read_world_stamp_info(base_dir)
        if (existing is not None
                and isinstance(existing.get('lineage'), int)
                and existing['lineage'] > int(lineage)):
            raise StaleLineageError(
                f'world stamp in {base_dir} is at lineage '
                f'{existing["lineage"]} but this process is at lineage '
                f'{int(lineage)}: refusing to move the stamp backward '
                '(this host belongs to an abandoned fork of the pod)')
        stamp['lineage'] = int(lineage)
        atomic_write_json(target, stamp)


def read_world_stamp_info(base_dir):
    """The full ``world.json`` payload (``num_devices`` plus the
    optional ``gen`` provenance), or None. A corrupt/absent stamp reads
    as None — same-world resume, never a crash."""
    import json
    path = os.path.join(os.path.abspath(base_dir), 'world.json')
    try:
        with open(path) as f:
            stamp = json.load(f)
        stamp['num_devices'] = int(stamp['num_devices'])
        return stamp
    except (OSError, ValueError, KeyError, TypeError):
        return None


def read_world_stamp(base_dir):
    """The ``num_devices`` recorded by :func:`write_world_stamp`, or
    None (no stamp — pre-elastic checkpoints resume as same-world)."""
    stamp = read_world_stamp_info(base_dir)
    return None if stamp is None else stamp['num_devices']


def wait_for_checkpoints():
    """Block until all in-flight async saves are durable on disk, then
    commit any deferred manifest — only after this returns is the last
    ``block=False`` save actually restorable."""
    global _PENDING_MANIFEST
    if _ASYNC_CKPTR is not None:
        try:
            _ASYNC_CKPTR.wait_until_finished()
        except Exception:
            _PENDING_MANIFEST = None  # that save never became durable
            raise
    _flush_pending_manifest()


def prune_checkpoints(base_dir, keep):
    """Keep only the ``keep`` newest distinct checkpoint epochs (orbax
    CheckpointManager-style retention; the reference keeps every epoch).
    Rank-0 only — pure filesystem, no barrier.

    Safe to call right after an async ``save_checkpoint(block=False)``
    because of two invariants this function RELIES on: (a) save_checkpoint
    waits for the previous async save before issuing a new one, so every
    finalized ``checkpoint-{e}`` name here is durable, and (b) the
    in-flight orbax write lives under a ``.orbax-checkpoint-tmp`` suffix
    the pattern below cannot match. If either invariant changes, call
    :func:`wait_for_checkpoints` first."""
    if keep is None or keep <= 0 or jax.process_index() != 0:
        return
    pat = re.compile(r'^checkpoint-(\d+)(\.pkl|\.manifest\.json)?$')
    by_epoch = {}
    for name in (os.listdir(base_dir) if os.path.isdir(base_dir) else ()):
        m = pat.match(name)
        if m:
            by_epoch.setdefault(int(m.group(1)), []).append(name)
    for epoch in sorted(by_epoch)[:-keep]:
        for name in by_epoch[epoch]:
            target = os.path.join(base_dir, name)
            if os.path.isdir(target):
                import shutil
                shutil.rmtree(target, ignore_errors=True)
            else:
                os.remove(target)
    # a REMOTE store holds its own copies of the same epochs — apply
    # the identical retention there (manifest first, so a crash mid-
    # prune leaves an uncommitted epoch, never a committed torso).
    # Housekeeping only: a store outage here must not kill the trainer.
    store = _store_for(base_dir)
    if _store.local_root(store) == os.path.abspath(str(base_dir)):
        return
    try:
        epochs = _manifest.manifest_epochs(store)
        for epoch in sorted(epochs)[:-keep]:
            manifest = _manifest.read_manifest(store, epoch)
            store.delete(epochs[epoch])
            for bkey in (sorted(manifest['blobs'])
                         if manifest is not None else ()):
                store.delete(bkey)
    except OSError:
        import logging
        logging.getLogger(__name__).warning(
            'store-side checkpoint prune failed; will retry at the '
            'next prune', exc_info=True)


def find_resume_epoch(base_dir, max_epoch):
    """Scan checkpoint-{epoch} downward from max_epoch (reference:
    pytorch_imagenet_resnet.py:162-167). Returns the epoch or None.

    Manifest-aware: an epoch whose manifest exists is COMMITTED and
    always eligible. Local files newer than the newest manifest but
    without one of their own are torn commits (the writer died between
    the blobs and the manifest) and are skipped. Files older than every
    manifest are legacy pre-manifest checkpoints and stay eligible —
    upgrading the code must not orphan existing checkpoints."""
    store = _store_for(base_dir)
    manifested = _store_guard(
        lambda: set(_manifest.manifest_epochs(store)))
    newest = max(manifested) if manifested else None
    for e in range(max_epoch, -1, -1):
        if e in manifested:
            return e
        present = (os.path.isdir(_ckpt_dir(base_dir, e))
                   or os.path.exists(_ckpt_dir(base_dir, e) + '.pkl'))
        if not present:
            continue
        if newest is not None and e > newest:
            import logging
            logging.getLogger(__name__).warning(
                'checkpoint-%d in %s has no manifest (torn commit); '
                'skipping it in the resume scan', e, base_dir)
            continue
        return e
    return None


def restore_checkpoint(base_dir, epoch, target_state, retry=None):
    """Restore into the structure of ``target_state``. ``retry``: an
    optional ``resilience.RetryPolicy`` for transient read failures (a
    corrupt/truncated file fails identically every attempt and still
    raises — that case belongs to :func:`auto_resume`'s scan-downward)."""
    if retry is not None:
        from kfac_pytorch_tpu.resilience.retry import call_with_retry
        return call_with_retry(
            lambda: _restore_checkpoint_once(base_dir, epoch, target_state),
            policy=retry, label=f'restore checkpoint-{epoch}')
    return _restore_checkpoint_once(base_dir, epoch, target_state)


def _restore_checkpoint_once(base_dir, epoch, target_state):
    store = _store_for(base_dir)
    manifest = _store_guard(lambda: _manifest.read_manifest(store, epoch))
    if manifest is not None:
        return _restore_manifested(base_dir, epoch, manifest, store,
                                   target_state)
    # legacy pre-manifest checkpoint: restore straight off the files
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX and os.path.isdir(path):
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, target_state)
    import pickle
    with open(path + '.pkl', 'rb') as f:
        return pickle.load(f)


def _verified_blob(store, key, spec):
    """Fetch one manifested blob and verify it against its recorded
    hash/size; ``(data, None)`` or ``(None, reason)``."""
    blob = _store_guard(lambda: store.get(key))
    if blob is None:
        return None, 'missing'
    if len(blob.data) != spec['size']:
        return None, 'size_mismatch'
    if _manifest.blob_sha256(blob.data) != spec['sha256']:
        return None, 'hash_mismatch'
    return blob.data, None


def _restore_manifested(base_dir, epoch, manifest, store, target_state):
    """Restore a COMMITTED epoch: every blob is re-verified against the
    manifest's content hash before a byte of it reaches the trainer —
    silent corruption surfaces here as :class:`CheckpointCorruptError`
    (which ``auto_resume`` turns into a scan-down), never as a
    mysterious unpickling/orbax failure three layers deeper."""
    import logging
    log = logging.getLogger(__name__)
    problems = []
    blobs = {}
    local = _store.local_root(store) == os.path.abspath(str(base_dir))
    for key in sorted(manifest['blobs']):
        data, reason = _verified_blob(store, key, manifest['blobs'][key])
        if reason is not None:
            log.warning('ckpt: corrupt blob key=%s epoch=%d reason=%s',
                        key, int(epoch), reason)
            problems.append((key, reason))
            continue
        blobs[key] = data
    if problems:
        raise CheckpointCorruptError(
            f'checkpoint-{epoch} failed manifest verification: '
            + ', '.join(f'{k} ({r})' for k, r in problems))
    if manifest.get('kind') == 'pickle':
        import pickle
        (data,) = blobs.values()
        return pickle.loads(data)
    # orbax tree: materialize verified bytes locally when the store is
    # remote (orbax restores from a directory), then restore as usual
    if not local:
        for key, data in blobs.items():
            target = os.path.join(os.path.abspath(str(base_dir)),
                                  *key.split('/'))
            os.makedirs(os.path.dirname(target), exist_ok=True)
            tmp = target + f'.tmp-{os.getpid()}'
            with open(tmp, 'wb') as f:
                f.write(data)
            os.replace(tmp, target)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(_ckpt_dir(base_dir, epoch), target_state)


def _saved_comm_err_zeros(path):
    """Zero arrays shaped like a saved ``KFACState.comm_err`` subtree —
    the restore placeholder for the comm_precision DOWNGRADE direction
    (lossy-era checkpoint into an fp32-configured run, see
    :func:`auto_resume`). ``None`` when the checkpoint carries no
    residual, or when orbax is unavailable (the pickle path restores
    without structure matching and never needs this)."""
    if not _HAS_ORBAX or not os.path.isdir(path):
        return None
    try:
        meta = ocp.StandardCheckpointer().metadata(path)
        err = (meta.get('kfac_state') or {}).get('comm_err')
        if not isinstance(err, dict) or not err:
            return None
        import jax.numpy as jnp
        return {key: jnp.zeros(m.shape, m.dtype)
                for key, m in err.items()}
    except Exception:  # noqa: BLE001 — metadata unreadable: not ours
        return None


def auto_resume(base_dir, max_epoch, target_state, retry=None):
    """Corruption-tolerant auto-resume: ``(restored_state, epoch)``, or
    ``(None, None)`` when nothing restorable exists. ``retry`` (a
    ``resilience.RetryPolicy``) is applied per restore attempt, so a
    TRANSIENT read hiccup on the newest checkpoint is retried in place
    rather than silently costing an epoch of progress to the
    scan-downward.

    Extends the reference's scan-downward resume
    (pytorch_imagenet_resnet.py:162-167) to UNREADABLE checkpoints: where
    a bare ``restore_checkpoint(find_resume_epoch(...))`` crashes the run
    on a truncated/corrupt file (e.g. a non-atomic write interrupted
    mid-save, or silent storage corruption), this keeps scanning to the
    next-older epoch — the same degrade-don't-die posture the in-jit
    health guard (health.py) applies to numerical blowups. Every skipped
    epoch is logged as a warning with the failure attached.
    """
    import logging
    log = logging.getLogger(__name__)
    epoch = find_resume_epoch(base_dir, max_epoch)
    while epoch is not None:
        try:
            return (restore_checkpoint(base_dir, epoch, target_state,
                                       retry=retry), epoch)
        except Exception:  # noqa: BLE001 — any unreadable ckpt: scan on
            # NOT necessarily corruption: a structure mismatch from a
            # checkpoint taken before an OPTIONAL state subtree existed
            # — no TrainState.health (pre-health code) and/or no
            # KFACState.comm_err (taken at fp32 before comm_precision
            # was enabled) — makes orbax reject the restore. Retry
            # against targets with those subtrees dropped: the trainer
            # re-seeds a None HealthState AND a None EF residual
            # host-side on the first step (training.py), so the
            # restored run is whole either way.
            for drop_err, drop_health, note in (
                    (True, False, 'predates comm_precision (no EF '
                                  'residual); residual starts at zero'),
                    (False, True, 'predates the health guard (no '
                                  'HealthState); counters start fresh'),
                    (True, True, 'predates the health guard and '
                                 'comm_precision; both start fresh')):
                fb = target_state
                if drop_err:
                    k = getattr(fb, 'kfac_state', None)
                    if k is None or getattr(k, 'comm_err', None) is None:
                        continue
                    fb = fb.replace(kfac_state=k.replace(comm_err=None))
                if drop_health:
                    if getattr(fb, 'health', None) is None:
                        continue
                    fb = fb.replace(health=None)
                try:
                    restored = restore_checkpoint(base_dir, epoch, fb,
                                                  retry=retry)
                    log.info('checkpoint-%d %s', epoch, note)
                    return restored, epoch
                except Exception:  # noqa: BLE001 — try the next target
                    pass
            # ... and the DOWNGRADE direction: the checkpoint CARRIES a
            # comm_err residual (taken under a lossy comm_precision) but
            # this run's target has none (fp32, or the knob reverted).
            # Build a zero placeholder from the checkpoint's own saved
            # shapes, restore, then discard the residual — it only
            # compensates a lossy wire, so dropping it loses one step's
            # quantization error at most, vs losing ALL progress to a
            # 'unreadable' restart-from-scratch.
            k = getattr(target_state, 'kfac_state', None)
            if k is not None and getattr(k, 'comm_err', None) is None:
                zeros = _saved_comm_err_zeros(_ckpt_dir(base_dir, epoch))
                if zeros is not None:
                    try:
                        restored = restore_checkpoint(
                            base_dir, epoch,
                            target_state.replace(
                                kfac_state=k.replace(comm_err=zeros)),
                            retry=retry)
                        restored = restored.replace(
                            kfac_state=restored.kfac_state.replace(
                                comm_err=None))
                        log.info(
                            'checkpoint-%d carries an EF residual '
                            '(comm_err) the current comm_precision does '
                            'not use; residual discarded', epoch)
                        return restored, epoch
                    except Exception:  # noqa: BLE001 — genuinely bad
                        pass
            log.warning(
                'checkpoint-%d in %s is unreadable; falling back to the '
                'next-older epoch', epoch, base_dir, exc_info=True)
        epoch = find_resume_epoch(base_dir, epoch - 1) if epoch > 0 else None
    return None, None


class PreemptionGuard:
    """Preemption-aware checkpoint trigger (beyond reference, SURVEY §5.3).

    Cloud TPU VMs are frequently preemptible: the platform delivers
    SIGTERM with a short grace window before killing the process. The
    reference's failure story is crash-stop + scan-downward auto-resume
    (examples/pytorch_imagenet_resnet.py:162-167), losing everything
    since the last epoch checkpoint. The guard converts the signal into
    a cooperative flag: trainers poll ``triggered`` at step boundaries,
    break out, save the CURRENT TrainState (step counter and K-FAC state
    included, so the LR schedule and factors resume exactly), and exit
    cleanly inside the grace window.

    Install once before the training loop; handlers chain to any
    previously-installed ones. In multi-host training poll
    :meth:`should_stop` (NOT the raw flag): hosts can receive the signal
    at different batch boundaries, and a rank leaving the loop alone
    would strand the others in a collective — ``should_stop`` OR-reduces
    the flag across processes so every rank exits at the same step.
    """

    def __init__(self, signals=None, sync_every=20):
        import signal as _signal

        self._flag = False
        self._stopped = False
        self.sync_every = max(1, sync_every)
        self._prev = {}
        for s in signals or (_signal.SIGTERM,):
            self._prev[s] = _signal.signal(s, self._handler)

    def _handler(self, signum, frame):
        self._flag = True
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def uninstall(self):
        """Put back the handlers that were installed before this guard.

        Without this every construction chains another handler for
        process lifetime — harmless for one trainer, but it leaks across
        tests and long-lived drivers (each leaked guard keeps its whole
        trainer state reachable, and a later SIGTERM still flips a flag
        nobody polls). Idempotent; un-nesting guards out of construction
        order restores each signal to what THIS guard saw, which may drop
        a later guard's handler — uninstall in reverse order.
        """
        import signal as _signal
        for s, prev in self._prev.items():
            # a None previous handler means "not installed from Python"
            # (signal.getsignal convention) — restore the default
            _signal.signal(s, prev if prev is not None else _signal.SIG_DFL)
        self._prev = {}

    @property
    def triggered(self):
        """Local flag only — safe to act on in single-process runs."""
        return self._flag

    def should_stop(self, step=None):
        """Cross-host consensus on the flag.

        Single process: the local flag. Multi-process: an OR-reduce over
        hosts, refreshed every ``sync_every`` steps when ``step`` is given
        (every call otherwise) — the collective runs on the same local
        step count on every host, so the calls pair up and all ranks
        observe the stop at the same batch boundary.
        """
        if jax.process_count() == 1:
            return self._flag
        if self._stopped:
            return True
        if step is not None and step % self.sync_every != 0:
            return False
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray(self._flag, np.int32))
        self._stopped = bool(np.any(flags))
        return self._stopped
