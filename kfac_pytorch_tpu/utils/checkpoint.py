"""Checkpoint / resume via orbax.

Parity and upgrade over the reference (examples/utils.py:11-18 rank-0
torch.save of {model, optimizer}; auto-resume by scanning
checkpoint-{epoch} downward, examples/pytorch_imagenet_resnet.py:162-167,
305-312). Upgrade: the K-FAC factor/decomposition state is checkpointed
too (the reference explicitly does NOT checkpoint m_A/m_G — factors
rebuild from running averages after resume; restoring them here makes
resume bit-faithful). Set ``include_kfac=False`` for reference-equivalent
behavior.
"""

import os
import re

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _ckpt_dir(base, epoch):
    return os.path.join(os.path.abspath(base), f'checkpoint-{epoch}')


def save_checkpoint(base_dir, epoch, state, include_kfac=True):
    """Write one checkpoint; only process 0 writes (rank-0 semantics,
    examples/utils.py:11-18)."""
    if jax.process_index() != 0:
        return
    payload = state
    if not include_kfac:
        payload = state.replace(kfac_state=None)
    os.makedirs(base_dir, exist_ok=True)
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, payload, force=True)
        ckptr.wait_until_finished()
    else:  # pragma: no cover
        import pickle
        with open(path + '.pkl', 'wb') as f:
            pickle.dump(jax.tree.map(np.asarray, payload), f)


def find_resume_epoch(base_dir, max_epoch):
    """Scan checkpoint-{epoch} downward from max_epoch (reference:
    pytorch_imagenet_resnet.py:162-167). Returns the epoch or None."""
    for e in range(max_epoch, -1, -1):
        if (os.path.isdir(_ckpt_dir(base_dir, e))
                or os.path.exists(_ckpt_dir(base_dir, e) + '.pkl')):
            return e
    return None


def restore_checkpoint(base_dir, epoch, target_state):
    """Restore into the structure of ``target_state``."""
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX and os.path.isdir(path):
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, target_state)
    import pickle  # pragma: no cover
    with open(path + '.pkl', 'rb') as f:
        return pickle.load(f)


class PreemptionGuard:
    """Preemption-aware checkpoint trigger (beyond reference, SURVEY §5.3).

    Cloud TPU VMs are frequently preemptible: the platform delivers
    SIGTERM with a short grace window before killing the process. The
    reference's failure story is crash-stop + scan-downward auto-resume
    (examples/pytorch_imagenet_resnet.py:162-167), losing everything
    since the last epoch checkpoint. The guard converts the signal into
    a cooperative flag: trainers poll ``triggered`` at step boundaries,
    break out, save the CURRENT TrainState (step counter and K-FAC state
    included, so the LR schedule and factors resume exactly), and exit
    cleanly inside the grace window.

    Install once before the training loop; handlers chain to any
    previously-installed ones. In multi-host training poll
    :meth:`should_stop` (NOT the raw flag): hosts can receive the signal
    at different batch boundaries, and a rank leaving the loop alone
    would strand the others in a collective — ``should_stop`` OR-reduces
    the flag across processes so every rank exits at the same step.
    """

    def __init__(self, signals=None, sync_every=20):
        import signal as _signal

        self._flag = False
        self._stopped = False
        self.sync_every = max(1, sync_every)
        self._prev = {}
        for s in signals or (_signal.SIGTERM,):
            self._prev[s] = _signal.signal(s, self._handler)

    def _handler(self, signum, frame):
        self._flag = True
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    @property
    def triggered(self):
        """Local flag only — safe to act on in single-process runs."""
        return self._flag

    def should_stop(self, step=None):
        """Cross-host consensus on the flag.

        Single process: the local flag. Multi-process: an OR-reduce over
        hosts, refreshed every ``sync_every`` steps when ``step`` is given
        (every call otherwise) — the collective runs on the same local
        step count on every host, so the calls pair up and all ranks
        observe the stop at the same batch boundary.
        """
        if jax.process_count() == 1:
            return self._flag
        if self._stopped:
            return True
        if step is not None and step % self.sync_every != 0:
            return False
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray(self._flag, np.int32))
        self._stopped = bool(np.any(flags))
        return self._stopped
