"""Checkpoint / resume via orbax.

Parity and upgrade over the reference (examples/utils.py:11-18 rank-0
torch.save of {model, optimizer}; auto-resume by scanning
checkpoint-{epoch} downward, examples/pytorch_imagenet_resnet.py:162-167,
305-312). Upgrade: the K-FAC factor/decomposition state is checkpointed
too (the reference explicitly does NOT checkpoint m_A/m_G — factors
rebuild from running averages after resume; restoring them here makes
resume bit-faithful). Set ``include_kfac=False`` for reference-equivalent
behavior.
"""

import os
import re

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _ckpt_dir(base, epoch):
    return os.path.join(os.path.abspath(base), f'checkpoint-{epoch}')


def save_checkpoint(base_dir, epoch, state, include_kfac=True):
    """Write one checkpoint; only process 0 writes (rank-0 semantics,
    examples/utils.py:11-18)."""
    if jax.process_index() != 0:
        return
    payload = state
    if not include_kfac:
        payload = state.replace(kfac_state=None)
    os.makedirs(base_dir, exist_ok=True)
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, payload, force=True)
        ckptr.wait_until_finished()
    else:  # pragma: no cover
        import pickle
        with open(path + '.pkl', 'wb') as f:
            pickle.dump(jax.tree.map(np.asarray, payload), f)


def find_resume_epoch(base_dir, max_epoch):
    """Scan checkpoint-{epoch} downward from max_epoch (reference:
    pytorch_imagenet_resnet.py:162-167). Returns the epoch or None."""
    for e in range(max_epoch, -1, -1):
        if (os.path.isdir(_ckpt_dir(base_dir, e))
                or os.path.exists(_ckpt_dir(base_dir, e) + '.pkl')):
            return e
    return None


def restore_checkpoint(base_dir, epoch, target_state):
    """Restore into the structure of ``target_state``."""
    path = _ckpt_dir(base_dir, epoch)
    if _HAS_ORBAX and os.path.isdir(path):
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, target_state)
    import pickle  # pragma: no cover
    with open(path + '.pkl', 'rb') as f:
        return pickle.load(f)
