"""Virtual host-platform forcing, shared by bench.py, scripts/, tests, and
the driver dry run.

The deployment environment pins ``JAX_PLATFORMS`` at interpreter start
(sitecustomize), so the env var cannot be used to escape to a virtual CPU
mesh — the platform must go through ``jax.config`` before any backend
initializes, and the device count through ``XLA_FLAGS`` (read lazily at
client init) or ``jax_num_cpu_devices``.
"""

import os
import re


def force_host_platform(platform=None, n_devices=None):
    """Force ``platform`` with ``n_devices`` virtual host devices.

    Must be called before any backend initializes (any ``jax.devices()`` or
    computation). Returns True when ``jax.devices()`` now satisfies the
    request; False means a backend was already initialized incompatibly —
    JAX cannot re-platform or grow the device count post-init, so the
    caller must re-exec in a fresh process. When neither argument is given
    this is a no-op returning True (backend stays lazy).
    """
    import jax

    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' in flags:
            flags = re.sub(
                r'--xla_force_host_platform_device_count=\d+',
                f'--xla_force_host_platform_device_count={n_devices}', flags)
        else:
            flags += f' --xla_force_host_platform_device_count={n_devices}'
        os.environ['XLA_FLAGS'] = flags
    if platform:
        jax.config.update('jax_platforms', platform)
        if platform == 'cpu' and n_devices is not None:
            try:
                jax.config.update('jax_num_cpu_devices', n_devices)
            except RuntimeError:
                pass  # already initialized; XLA_FLAGS may still have taken
    if not platform:
        return True  # nothing to verify without forcing a platform init
    devices = jax.devices()
    ok = all(d.platform == platform
             for d in devices[:n_devices or len(devices)])
    if n_devices is not None:
        ok = ok and len(devices) >= n_devices
    return ok
