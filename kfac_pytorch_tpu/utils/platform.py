"""Virtual host-platform forcing, shared by bench.py, scripts/, tests, and
the driver dry run.

The deployment environment pins ``JAX_PLATFORMS`` at interpreter start
(sitecustomize), so the env var cannot be used to escape to a virtual CPU
mesh — the platform must go through ``jax.config`` before any backend
initializes, and the device count through ``XLA_FLAGS`` (read lazily at
client init) or ``jax_num_cpu_devices``.
"""

import os
import re


class BackendHang(RuntimeError):
    """Backend init never answered (tunnel down / wedged init lock)."""


class BackendInitError(RuntimeError):
    """Backend init ran and raised — re-probing or re-exec cannot help."""


def probe_backend(timeout_s=180, retries=1, on_wait=None):
    """Initialize the backend under a watchdog thread.

    ``jax.devices()`` HANGS (not errors) when the chip tunnel is down, so
    probe it on a daemon thread and re-join up to ``retries`` times —
    backend init is a process singleton, so later joins simply extend the
    wait window in case the tunnel comes back. ``on_wait(attempt)`` is
    called after each unanswered window. Raises :class:`BackendHang` when
    the backend never answers, :class:`BackendInitError` when its init
    raised."""
    import threading

    import jax

    result = {}

    def probe():
        try:
            result['devices'] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report any init failure
            result['error'] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    for attempt in range(retries):
        t.join(timeout_s)
        if 'devices' in result:
            return result['devices']
        if 'error' in result:
            raise BackendInitError(f'backend init failed: {result["error"]}')
        if on_wait is not None:
            on_wait(attempt)
    raise BackendHang(
        f'backend unavailable: jax.devices() hung for '
        f'{retries * timeout_s}s (tunnel down?)')


def force_host_platform(platform=None, n_devices=None):
    """Force ``platform`` with ``n_devices`` virtual host devices.

    Must be called before any backend initializes (any ``jax.devices()`` or
    computation). Returns True when ``jax.devices()`` now satisfies the
    request; False means a backend was already initialized incompatibly —
    JAX cannot re-platform or grow the device count post-init, so the
    caller must re-exec in a fresh process. When neither argument is given
    this is a no-op returning True (backend stays lazy).
    """
    import jax

    # If another thread is wedged inside a hung backend init (a watchdog
    # probe of an unreachable accelerator), jax.config.update below would
    # block on the same init lock forever — detect it and bail to the
    # caller's fresh-process fallback instead.
    try:
        from jax._src import xla_bridge as _xb
        lock = getattr(_xb, '_backend_lock', None)
        if lock is not None:
            if not lock.acquire(timeout=10):
                return False
            lock.release()
    except ImportError:  # private module moved — skip the fast-fail check
        pass

    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' in flags:
            flags = re.sub(
                r'--xla_force_host_platform_device_count=\d+',
                f'--xla_force_host_platform_device_count={n_devices}', flags)
        else:
            flags += f' --xla_force_host_platform_device_count={n_devices}'
        os.environ['XLA_FLAGS'] = flags
    if platform:
        jax.config.update('jax_platforms', platform)
        if platform == 'cpu' and n_devices is not None:
            try:
                jax.config.update('jax_num_cpu_devices', n_devices)
            except RuntimeError:
                pass  # already initialized; XLA_FLAGS may still have taken
            except AttributeError:
                pass  # pre-0.5 jax: XLA_FLAGS above is the only mechanism
    if not platform:
        return True  # nothing to verify without forcing a platform init
    try:
        # watchdog, not a bare jax.devices(): if another thread is already
        # wedged inside a hung backend init (e.g. a probe of an
        # unreachable accelerator), this would block on the init lock
        # forever — time out and let the caller re-exec fresh instead
        devices = probe_backend(timeout_s=60)
    except BackendHang:
        return False  # wedged init in this process only; re-exec helps
    ok = all(d.platform == platform
             for d in devices[:n_devices or len(devices)])
    if n_devices is not None:
        ok = ok and len(devices) >= n_devices
    return ok
