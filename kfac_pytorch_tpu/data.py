"""Input pipelines.

The reference uses torchvision datasets + DistributedSampler + a
persistent-worker MultiEpochsDataLoader (examples/pytorch_cifar10_resnet.py:
154-192, examples/utils.py:93-121). Here:

- batches are host numpy; the mesh shards them (the DistributedSampler
  equivalent is the P('batch') in_spec of the train step);
- CIFAR-10/100 load from the standard binary/pickle archives if a data dir
  is given; otherwise deterministic synthetic data keeps every entrypoint
  runnable in a dataset-free environment (this container has no datasets
  and no egress);
- augmentation (pad-crop + horizontal flip, the reference's transform
  stack, examples/pytorch_cifar10_resnet.py:157-166) is vectorized numpy;
- the loader is an infinite persistent iterator — MultiEpochsDataLoader
  semantics by construction.
"""

import os
import pickle
import queue
import tarfile
import threading

import numpy as np

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

def synthetic_classification(n, shape, num_classes, seed=0):
    """Deterministic synthetic dataset with class-dependent means so a
    model can actually fit it (loss decreases; useful for smoke
    convergence runs)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n)
    means = rng.randn(num_classes, *shape).astype(np.float32) * 0.5
    x = (rng.randn(n, *shape).astype(np.float32) + means[labels])
    return x, labels.astype(np.int64)


def load_cifar10(data_dir):
    """Read the standard cifar-10-batches-py pickles (the files
    torchvision's CIFAR10 uses)."""
    base = os.path.join(data_dir, 'cifar-10-batches-py')
    if not os.path.isdir(base):
        archive = os.path.join(data_dir, 'cifar-10-python.tar.gz')
        if os.path.exists(archive):
            with tarfile.open(archive) as tf:
                tf.extractall(data_dir)
    xs, ys = [], []
    for name in [f'data_batch_{i}' for i in range(1, 6)]:
        with open(os.path.join(base, name), 'rb') as f:
            d = pickle.load(f, encoding='bytes')
        xs.append(d[b'data'])
        ys.extend(d[b'labels'])
    train_x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    with open(os.path.join(base, 'test_batch'), 'rb') as f:
        d = pickle.load(f, encoding='bytes')
    test_x = d[b'data'].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return ((train_x, np.asarray(ys, np.int64)),
            (test_x, np.asarray(d[b'labels'], np.int64)))


def get_cifar(data_dir=None, num_classes=10, synthetic_size=2048):
    """(train, val) arrays: real CIFAR if available, else synthetic."""
    if data_dir and num_classes == 10:
        try:
            return load_cifar10(data_dir)
        except (FileNotFoundError, OSError):
            pass
    # one draw, one set of class means, then split — train and val must
    # come from the SAME distribution or validation is unlearnable noise
    n_val = synthetic_size // 4
    x, y = synthetic_classification(synthetic_size + n_val, (32, 32, 3),
                                    num_classes, seed=1)
    return (x[:synthetic_size], y[:synthetic_size]), \
        (x[synthetic_size:], y[synthetic_size:])


# ---------------------------------------------------------------------------
# Augmentation + iteration
# ---------------------------------------------------------------------------

def _normalize(x):
    if x.dtype == np.uint8:
        x = x.astype(np.float32) / 255.0
        x = (x - CIFAR10_MEAN) / CIFAR10_STD
    return x.astype(np.float32)


def augment_cifar(rng, x):
    """Pad-4 random crop + horizontal flip
    (reference transform stack: examples/pytorch_cifar10_resnet.py:157-163).
    Uses the native batched kernel (native/kfac_native.cc) when available;
    numpy fallback otherwise."""
    n, h, w, c = x.shape
    offs = rng.randint(0, 9, size=(n, 2)).astype(np.int32)
    flips = (rng.rand(n) < 0.5)
    from kfac_pytorch_tpu import native_lib
    out = native_lib.augment_crop_flip(
        x.astype(np.float32, copy=False), offs, flips.astype(np.uint8))
    if out is not None:
        return out
    xp = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode='reflect')
    out = np.empty_like(x)
    for i in range(n):
        oy, ox = offs[i]
        win = xp[i, oy:oy + h, ox:ox + w]
        out[i] = win[:, ::-1] if flips[i] else win
    return out


class PrefetchIterator:
    """Iterator over prefetched batches with DETERMINISTIC release.

    Wraps the prefetch generator so call sites don't have to rely on
    CPython refcounting to finalize it: ``close()`` (idempotent) stops
    the producer thread immediately, and the object is its own context
    manager (``with loader.epoch() as it: ...``). Without an explicit
    close, a pinned iterator (stored traceback, reference cycle,
    non-refcounted runtime) would leave the daemon producer spinning on
    put timeouts, holding up to ``depth`` batches in memory.
    """

    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self):
        self._gen.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def prefetch(gen, depth=2):
    """Run a batch generator in a background thread, ``depth`` items ahead
    — host batch assembly (gather + normalize + augmentation) overlaps
    device execution instead of serializing with it. This is the
    persistent-worker MultiEpochsDataLoader capability (reference:
    examples/utils.py:93-121, num_workers>0) delivered the single-process
    TPU way: one producer thread and a bounded queue, no worker
    processes to fork or keep alive. Exceptions in the producer re-raise
    at the consuming site; the yielded sequence is identical to ``gen``.

    Returns a :class:`PrefetchIterator`: abandoning it releases the
    producer thread when the wrapped generator finalizes (promptly under
    CPython refcounting), and ``close()`` / ``with`` releases it
    deterministically."""
    return PrefetchIterator(_prefetch_gen(gen, depth))


def _prefetch_gen(gen, depth):
    if depth <= 0:
        yield from gen
        return
    q = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put(msg):
        # stop-aware put: an abandoned consumer (early break / generator
        # close) would otherwise leave this thread blocked in q.put
        # forever, pinning the queue's batches and the source generator
        while not stop.is_set():
            try:
                q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in gen:
                if not put(('item', item)):
                    gen.close()
                    return
            put(('end', None))
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            put(('exc', e))

    t = threading.Thread(target=worker, daemon=True, name='kfac-prefetch')
    t.start()
    try:
        while True:
            kind, payload = q.get()
            if kind == 'end':
                break
            if kind == 'exc':
                raise payload
            yield payload
    finally:
        stop.set()


class Loader:
    """Persistent shuffling batch iterator (drop-last, reshuffle per epoch).

    ``shard=(index, count)`` restricts iteration to this process's slice of
    every epoch permutation — the multi-host DistributedSampler (reference:
    examples/pytorch_cifar10_resnet.py:180-192): all processes draw the
    same permutation (same seed) and take disjoint contiguous slices, so
    ``batch_size`` here is the *per-process* batch. Defaults to
    ``(jax.process_index(), jax.process_count())``.
    """

    def __init__(self, x, y, batch_size, train=True, augment=None, seed=0,
                 shard=None):
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.train = train
        self.augment = augment
        self.rng = np.random.RandomState(seed)
        if shard is None:
            import jax
            shard = (jax.process_index(), jax.process_count())
        self.shard_index, self.shard_count = shard
        self.steps_per_epoch = len(x) // (batch_size * self.shard_count)

    def epoch(self, prefetch_depth=2, retry=None):
        """One epoch of batches, assembled ``prefetch_depth`` ahead on a
        background thread (:func:`prefetch`; 0 = synchronous). The batch
        sequence is identical at any depth: each epoch draws a child RNG
        SEED from the persistent stream exactly once up front, so how far
        the producer has run ahead (or where the consumer abandoned the
        epoch) cannot perturb later epochs' randomness.

        ``retry``: an optional ``resilience.RetryPolicy`` for the
        next-batch path — a transient producer failure (flaky storage
        read, injected ``KFAC_FAULT_DATA_STEP`` EIO) rebuilds the epoch
        pipeline from the SAME seed and fast-forwards past the batches
        already delivered, so the consumer sees the exact unfaulted
        sequence (``resilience.retry.resumable_iter``). A persistent
        failure still raises once the policy is exhausted.
        """
        seed = self.rng.randint(1 << 31)

        def make():
            return prefetch(self._epoch_sync(np.random.RandomState(seed)),
                            depth=prefetch_depth)

        if retry is None:
            return make()
        from kfac_pytorch_tpu.resilience.retry import resumable_iter
        return PrefetchIterator(resumable_iter(make, policy=retry,
                                               label='next-batch'))

    def _epoch_sync(self, rng):
        idx = np.arange(len(self.x))
        if self.train:
            rng.shuffle(idx)
        per = len(self.x) // self.shard_count
        idx = idx[self.shard_index * per:(self.shard_index + 1) * per]
        from kfac_pytorch_tpu import faults
        for s in range(self.steps_per_epoch):
            if os.environ.get(faults.ENV_DATA):
                # chaos drill: one transient EIO out of the producer at
                # the configured batch index (faults.maybe_data_fault)
                faults.maybe_data_fault(s)
            sel = idx[s * self.batch_size:(s + 1) * self.batch_size]
            bx = _normalize(self.x[sel])
            if self.train and self.augment is not None:
                bx = self.augment(rng, bx)
            yield {'input': bx, 'label': self.y[sel]}
