"""ctypes bindings for the native runtime library (native/kfac_native.cc).

Builds lazily with cc if the shared object is missing (no pybind11 in
this image; plain C linkage + ctypes). Every entry point has a numpy
fallback in pure Python — the native path is an acceleration, not a
requirement (mirrors how the reference keeps tcmm optional,
kfac/utils.py:7).
"""

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), '..', 'native')
_LIB_PATH = os.path.join(_DIR, 'libkfac_native.so')
_lib = None
_tried = False


def _build():
    src = os.path.join(_DIR, 'kfac_native.cc')
    subprocess.run(['c++', '-O2', '-shared', '-fPIC', '-o', _LIB_PATH, src],
                   check=True, capture_output=True)


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_LIB_PATH):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.block_partition.restype = ctypes.c_double
        lib.block_partition.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.lpt_assign.restype = ctypes.c_double
        lib.lpt_assign.argtypes = lib.block_partition.argtypes
        lib.augment_crop_flip.restype = None
        lib.augment_crop_flip.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float)]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def block_partition(costs, num_devices):
    lib = get_lib()
    costs = np.ascontiguousarray(costs, np.float64)
    owners = np.zeros(len(costs), np.int64)
    if lib is None:
        from kfac_pytorch_tpu.parallel import partition
        return partition.block_partition(costs, num_devices)
    lib.block_partition(_ptr(costs, ctypes.c_double), len(costs),
                        num_devices, _ptr(owners, ctypes.c_int64))
    return owners


def lpt_assign(costs, num_devices):
    lib = get_lib()
    costs = np.ascontiguousarray(costs, np.float64)
    owners = np.zeros(len(costs), np.int64)
    if lib is None:
        from kfac_pytorch_tpu.parallel import partition
        return partition.balanced_assign(costs, num_devices)
    lib.lpt_assign(_ptr(costs, ctypes.c_double), len(costs), num_devices,
                   _ptr(owners, ctypes.c_int64))
    return owners


def augment_crop_flip(x, offs, flips, pad=4):
    """Native batched pad-crop-flip; x: [N,H,W,C] float32."""
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, np.float32)
    offs = np.ascontiguousarray(offs, np.int32)
    flips = np.ascontiguousarray(flips, np.uint8)
    out = np.empty_like(x)
    n, h, w, c = x.shape
    lib.augment_crop_flip(_ptr(x, ctypes.c_float), n, h, w, c, pad,
                          _ptr(offs, ctypes.c_int32),
                          _ptr(flips, ctypes.c_uint8),
                          _ptr(out, ctypes.c_float))
    return out
