"""KFAC-aware Flax linen layers.

The reference instruments stock ``nn.Linear``/``nn.Conv2d`` with hooks
(reference: kfac/kfac_preconditioner_base.py:132-149). Here the layers
themselves carry the capture machinery (see ``capture.py``): they sow their
input into the ``'kfac_a'`` collection and add a differentiable zero tap to
their pre-activation output. When neither capture collection is active the
layers are exactly plain dense/conv — zero overhead.

Compute dtype may be bf16 (MXU-native) while params and factor statistics
stay fp32.
"""

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kfac_pytorch_tpu import capture

default_kernel_init = linen.initializers.lecun_normal()


def _overwrite(prev, new):
    # sow reducer: keep the latest call's value (matches hook overwrite
    # semantics for re-entrant modules, kfac_preconditioner_base.py:122-130).
    return new


class _KFACLayerMixin:
    """Shared capture plumbing for Dense/Conv."""

    def _capture_input(self, x):
        if self.kfac_enabled:
            self.sow(capture.ACTS, 'a', x, reduce_fn=_overwrite,
                     init_fn=lambda: ())

    def _tap_output(self, y):
        if not self.kfac_enabled:
            return y
        has_tap = (self.is_mutable_collection(capture.TAPS)
                   or self.has_variable(capture.TAPS, 'g'))
        if not has_tap:
            return y
        tap = self.variable(capture.TAPS, 'g',
                            lambda: jnp.zeros(y.shape, y.dtype))
        return y + tap.value


class Dense(linen.Module, _KFACLayerMixin):
    """Dense layer with K-FAC capture (reference hook target: ``nn.Linear``).

    Params: ``kernel [d_in, d_out]``, optional ``bias [d_out]``.
    """
    features: int
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = default_kernel_init
    bias_init: Callable = linen.initializers.zeros_init()
    kfac_enabled: bool = True

    @linen.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        kernel = self.param('kernel', self.kernel_init, (d_in, self.features),
                            self.param_dtype)
        bias = (self.param('bias', self.bias_init, (self.features,),
                           self.param_dtype) if self.use_bias else None)
        if self.kfac_enabled:
            capture.report_layer(capture.LayerMeta(
                name='/'.join(self.path), path=tuple(self.path), kind='dense',
                use_bias=self.use_bias,
                in_dim=d_in + int(self.use_bias), out_dim=self.features,
                kernel_shape=(d_in, self.features)))
        self._capture_input(x)
        x, kernel = linen.dtypes.promote_dtype(x, kernel, dtype=self.dtype)
        y = lax.dot_general(x, kernel, (((x.ndim - 1,), (0,)), ((), ())))
        if bias is not None:
            y = y + jnp.asarray(bias, y.dtype)
        return self._tap_output(y)


class Conv(linen.Module, _KFACLayerMixin):
    """2-D convolution with K-FAC capture (reference hook target:
    ``nn.Conv2d``). NHWC inputs, HWIO kernel.

    Factor A's im2col (ops.compute_a_conv) uses exactly the geometry
    declared here; ``padding`` is resolved to explicit pairs at capture
    time so 'SAME'/'VALID' match what the conv executed.
    """
    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Union[str, Sequence] = 'SAME'
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = default_kernel_init
    bias_init: Callable = linen.initializers.zeros_init()
    kfac_enabled: bool = True

    @linen.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        c_in = x.shape[-1]
        kernel = self.param('kernel', self.kernel_init,
                            (kh, kw, c_in, self.features), self.param_dtype)
        bias = (self.param('bias', self.bias_init, (self.features,),
                           self.param_dtype) if self.use_bias else None)
        pads = capture.canonical_padding(
            x.shape[1:3], self.kernel_size, self.strides, self.padding)
        if self.kfac_enabled:
            capture.report_layer(capture.LayerMeta(
                name='/'.join(self.path), path=tuple(self.path), kind='conv',
                use_bias=self.use_bias,
                in_dim=kh * kw * c_in + int(self.use_bias),
                out_dim=self.features,
                kernel_shape=(kh, kw, c_in, self.features),
                kernel_size=(kh, kw), strides=tuple(self.strides),
                padding=pads))
        self._capture_input(x)
        x, kernel = linen.dtypes.promote_dtype(x, kernel, dtype=self.dtype)
        y = lax.conv_general_dilated(
            x, kernel, window_strides=tuple(self.strides),
            padding=list(pads), dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
        if bias is not None:
            y = y + jnp.asarray(bias, y.dtype)
        return self._tap_output(y)
