"""Tunnel-independent analytic performance model (VERDICT r4 #1).

Four rounds of BENCH_r0N.json came back null because the chip tunnel
never answered during a driver run (logs/onchip/watch_tunnel.log is the
continuous no-answer record). This module produces the falsifiable
stand-in: a per-phase cost model that PREDICTS steady-state s/iter and
imgs/s/chip for each K-FAC variant on the one real chip this project
targets (TPU v5e / "v5 lite"), against the reference's measured 1-GPU
anchor of 0.487 s/iter at bs 32 (reference: scripts/time_breakdown.py:26).

Every prediction is clearly labeled ``predicted_not_measured`` and is
assembled from exactly three ingredient classes, each pinned and
auditable:

1. **Per-phase FLOPs / bytes from XLA cost analysis** — the compiled
   train-step programs of each variant are differenced along the same
   cumulative-ablation ladder the measured breakdown uses
   (utils/profiling.exclude_parts_breakdown; reference
   scripts/parse_logs.py:44-73). Derived once on the CPU backend by
   ``scripts/derive_perf_inputs.py`` (flop counts of dot/conv ops are
   backend-independent; LAPACK custom calls are NOT counted there, so
   the two decomposition phases below use ingredient 2/3 instead) and
   committed as ``data/perf_inputs_resnet50_bs32.json``.
2. **Fenced chip constants** — the round-2 on-chip measurements taken
   with the host-fence methodology (logs/onchip/manual_seq.log; plain
   ``block_until_ready`` does not fence on the tunneled platform):
   batched XLA QDWH eigh [4,2304] = 9.85 s and [8,512] = 1.64 s. The
   eigen variants' full-decomposition phase is extrapolated from these
   two points (power law, form stated on the function).
3. **Stated roofline assumptions** — phases with no fenced measurement
   (conv fwd/bwd, factor GEMMs, Cholesky) get
   ``t = max(flops / (eff * peak), bytes / (hbm_eff * bw))`` under
   THREE efficiency scenarios (optimistic / central / conservative).
   The scenarios bracket the prediction; a fenced measurement outside
   the [optimistic, conservative] band falsifies the model, one inside
   narrows it.

Single-chip only, matching the anchor (no collectives; the DP-vs-MPD
comm story is separately compiler-verified by scripts/comm_count.py).

The bench harness (bench.py) embeds ``predict_block()`` in its output
extras BEFORE probing the backend, so BENCH_r05.json carries these
numbers even on a tunnel-down round. Pinned by tests/test_perf_model.py.
"""

import json
import math
import os

#: reference 1-GPU K-FAC iteration at bs 32 (scripts/time_breakdown.py:26)
BASELINE_ITER_S = 0.487
BATCH = 32

#: TPU v5e ("v5 lite") public per-chip figures: dense bf16 peak FLOP/s
#: and HBM bandwidth (cloud TPU docs / scaling-book numbers).
PEAK_BF16 = 197e12
HBM_BW = 819e9

#: Fenced on-chip eigh measurements (logs/onchip/manual_seq.log,
#: 2026-07-31, TPU v5 lite0, f32, host-fence methodology): (rows, dim,
#: seconds of pure compute after subtracting the wire-only transfer).
FENCED_EIGH_POINTS = ((4, 2304, 9.8486), (8, 512, 1.6368))

#: Fenced on-chip attention datapoint (logs/onchip/
#: queue_0731_0346.flash_sweep.log): XLA fwd+bwd causal attention,
#: B=1 H=8 D=64 L=16384 in 103.64 ms -> ~8e12 FLOP/s achieved (~4% of
#: peak). Recorded as the measured lower anchor for SKINNY programs —
#: not used to set the conv scenarios (bs-32 convs are MXU-shaped), but
#: it bounds how wrong "conservative" can be for thin shapes.
FENCED_ATTN_NOTE = dict(program='xla_attention_fwd_bwd_causal',
                        config='B1_H8_D64_L16384', seconds=0.10364,
                        approx_flops=8.25e11, achieved_flops=8.0e12)

#: Roofline scenarios: (MXU efficiency for bf16-input matmul/conv work,
#: HBM efficiency). Central 0.4 is the scaling-book's "well-mapped
#: model" band midpoint; conservative 0.2 covers fusion/layout misses;
#: optimistic 0.6 is near the practical ceiling for conv nets.
SCENARIOS = {
    'optimistic': (0.60, 0.90),
    'central': (0.40, 0.70),
    'conservative': (0.20, 0.50),
}

#: f32-accumulating GEMMs on f32 inputs (precondition / refresh /
#: Cholesky phases) cannot use the bf16 MXU path directly; assumed rate
#: = bf16 rate / F32_PENALTY (stated assumption, v5e has no native f32
#: matmul unit).
F32_PENALTY = 4.0

#: analytic FLOPs of psd_inverse per dxd matrix: potrf d^3/3 + two
#: full-RHS triangular solves d^3 each (ops/linalg.py:30-41). The CPU
#: derivation counts these as 0 (LAPACK custom calls), so the Cholesky
#: phase is reconstructed analytically from the plan's bucket table.
CHOLESKY_FLOPS_PER_MATRIX = lambda d: (7.0 / 3.0) * d ** 3  # noqa: E731

#: analytic FLOPs of the ITERATIVE decomposition kernels per dxd matrix
#: (the inverse-free ladder rungs, ops/linalg.py) — pure batched GEMMs,
#: so unlike QDWH eigh they roofline honestly at the MXU rate:
#:
#: - subspace_eigh, per tracking step (default 2): X@Q + Q^T(XQ) +
#:   Q@K (3 GEMMs, 2d^3 each) and CholeskyQR2 = 2 x (Gram 2d^3 +
#:   cholesky d^3/3 + triangular solve d^3) ~= 6.7d^3 -> ~12.7d^3 per
#:   step; plus the final Rayleigh X@Q + diag contraction ~= 3d^3.
#: - newton_schulz_inverse, per iteration (default 2): A@X + X@(2I-AX)
#:   (2 GEMMs, 2d^3 each) -> 4d^3; plus the residual check A@X ~= 2d^3
#:   (the Cholesky fallback sits behind a lax.cond and costs nothing on
#:   the healthy path).
SUBSPACE_FLOPS_PER_MATRIX = \
    lambda d, steps=2: (12.7 * steps + 3.0) * d ** 3  # noqa: E731
NEWTON_SCHULZ_FLOPS_PER_MATRIX = \
    lambda d, iters=2: (4.0 * iters + 2.0) * d ** 3   # noqa: E731

#: HBM-byte multiplier of the FUSED capture path (ops/pallas_capture,
#: ISSUE 19) relative to the unfused ComputeFactor bytes: the fused
#: kernels never materialize the im2col patch matrix in HBM (conv-A's
#: dominant traffic — written once by extract_patches, read back by the
#: GEMM) and fold the EMA read-modify-write into the accumulator
#: epilogue instead of a separate elementwise pass. FLOPs are unchanged
#: (the same statistic GEMMs run either way), so the fused rung only
#: moves the memory-bound side of the roofline. 0.5 is a stated
#: assumption bracketing "patch matrix round trip gone, activations
#: still stream once"; the on-chip microbench re-baselines it when the
#: tunnel answers.
CAPTURE_FUSION_BYTES_FACTOR = 0.5

#: TPU v5e ICI per-chip interconnect bandwidth, one direction
#: (~45 GB/s per link, public scaling-book figure) — the stated
#: assumption behind the per-axis comm scenarios. DCN (cross-slice)
#: rides a ~25 Gb/s-class NIC share per chip.
ICI_BW = 4.5e10
DCN_BW = 3.1e9

#: link-efficiency scenarios for the collective comm model (fraction of
#: the wire rate an all-reduce/reduce-scatter actually sustains at the
#: factor payload sizes; bracketed the same way SCENARIOS brackets the
#: MXU roofline).
COMM_SCENARIOS = {
    'optimistic': 0.85,
    'central': 0.70,
    'conservative': 0.45,
}

_INPUTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            'data', 'perf_inputs_resnet50_bs32.json')


def load_inputs(path=None):
    with open(path or _INPUTS_PATH) as f:
        return json.load(f)


def eigh_time_model():
    """Two-point power-law fit of the fenced batched-eigh times.

    Form: ``t = c * rows * dim**p`` — batch-linear (conservative: the
    MXU may overlap small batches) with the dim exponent solved from the
    two fenced points. QDWH is iteration-bound, not flop-bound, which is
    WHY this phase gets measured points instead of a roofline (the
    roofline predicts ~milliseconds; the chip says seconds). Returns
    ``(c, p, fn)`` with ``fn(rows, dim) -> seconds``. Extrapolation
    beyond [512, 2304] is labeled as such in the assumptions block.
    """
    (b1, d1, t1), (b2, d2, t2) = FENCED_EIGH_POINTS
    p = math.log((t1 / b1) / (t2 / b2)) / math.log(d1 / d2)
    c = (t1 / b1) / d1 ** p
    return c, p, lambda rows, dim: c * rows * dim ** p


def _phase_time(flops, bytes_, eff, hbm_eff, rate=PEAK_BF16):
    """Roofline: compute-bound vs memory-bound, whichever dominates."""
    t_c = flops / (eff * rate) if flops else 0.0
    t_m = bytes_ / (hbm_eff * HBM_BW) if bytes_ else 0.0
    return max(t_c, t_m)


def phase_costs(inputs):
    """Difference the per-program cost-analysis totals into the ledger
    phases (the measured breakdown's taxonomy, reference
    scripts/time_breakdown.py:24-27 names).

    Returns {phase: (flops, bytes)} plus the bucket table. 'inverse_chol'
    is analytic (see CHOLESKY_FLOPS_PER_MATRIX); 'inverse_eigh' carries
    the bucket table for the fenced time model instead of flops.
    """
    prog = inputs['programs']

    def diff(a, b):
        return (max(prog[a]['flops'] - prog[b]['flops'], 0.0),
                max(prog[a]['bytes'] - prog[b]['bytes'], 0.0))

    buckets = inputs['buckets']  # [[rows, dim], ...]
    chol_flops = sum(r * CHOLESKY_FLOPS_PER_MATRIX(d) for r, d in buckets)
    # bytes: read factors + write inverses, f32: 2 * rows * d^2 * 4 B
    chol_bytes = sum(2 * r * d * d * 4 for r, d in buckets)
    # iterative decomp_impl rungs: reads factor + seed, writes result
    sub_flops = sum(r * SUBSPACE_FLOPS_PER_MATRIX(d) for r, d in buckets)
    ns_flops = sum(r * NEWTON_SCHULZ_FLOPS_PER_MATRIX(d)
                   for r, d in buckets)
    iter_bytes = sum(3 * r * d * d * 4 for r, d in buckets)
    return {
        'model': (prog['sgd']['flops'], prog['sgd']['bytes']),
        'precondition': diff('inverse_dp_base', 'sgd'),
        'precondition_eigen': diff('eigen_dp_base', 'sgd'),
        'factor': diff('inverse_dp_factor', 'inverse_dp_base'),
        'refresh': diff('eigen_dp_refresh', 'eigen_dp_factor'),
        'ekfac_scales': diff('ekfac_factor', 'eigen_dp_factor'),
        'inverse_chol': (chol_flops, chol_bytes),
        'inverse_subspace': (sub_flops, iter_bytes),
        'inverse_ns': (ns_flops, iter_bytes),
    }


def decomp_impl_priors(block, method, anchor='central'):
    """{rung: predicted decomposition seconds} for the method's
    decomp_impl ladder, from a ``predict_block()`` dict — the
    autotuner's seeding input (``KnobController._seed_decomp_impl``).
    eigh: fenced QDWH full vs the subspace tracker; cholesky: analytic
    Cholesky vs Newton-Schulz. Returns {} when the block carries no
    usable phases (the tuner then probes from the configured rung)."""
    try:
        ph = block['scenarios'][anchor]['phases_s']
    except (KeyError, TypeError):
        return {}
    if method == 'eigh':
        out = {'xla': ph.get('ComputeInverse_eigh_full'),
               'subspace': ph.get('ComputeInverse_subspace')}
    elif method == 'cholesky':
        out = {'xla': ph.get('ComputeInverse_chol'),
               'newton_schulz': ph.get('ComputeInverse_ns')}
    else:
        return {}
    if any(v is None for v in out.values()):
        return {}
    return {k: float(v) for k, v in out.items()}


def capture_impl_priors(block, anchor='central'):
    """{rung: predicted ComputeFactor seconds} for the capture_impl
    ladder, from a ``predict_block()`` dict — the autotuner's seeding
    input (``KnobController._seed_capture_impl``). Unfused XLA capture
    vs the fused Pallas kernels (same GEMM FLOPs, HBM bytes scaled by
    CAPTURE_FUSION_BYTES_FACTOR). Returns {} when the block carries no
    usable phases (the tuner then probes from the configured rung)."""
    try:
        ph = block['scenarios'][anchor]['phases_s']
    except (KeyError, TypeError):
        return {}
    out = {'xla': ph.get('ComputeFactor'),
           'pallas': ph.get('ComputeFactor_pallas')}
    if any(v is None for v in out.values()):
        return {}
    return {k: float(v) for k, v in out.items()}


def predict(inputs=None):
    """Predicted steady-state s/iter + imgs/s per variant per scenario.

    Cadences modeled (matching bench.py's measured legs):
      sgd; inverse_dp freq 1 (the headline config: factor+inverse every
      step, the reference-breakdown setting); inverse_dp freq 10 (the
      deployed cadence, pytorch_imagenet_resnet.py:94); eigen_dp freq 10
      cold (the reference DEFAULT variant at its deployed cadence —
      predicted unusable on TPU, the quantified eigen-path gap);
      eigen_dp freq 10 + basis_update_freq 100 (amortized rescue);
      ekfac freq 10 + basis 100 (amortized + per-example corrected
      scales).
    """
    inputs = inputs or load_inputs()
    ph = phase_costs(inputs)
    _, _, eigh_t = eigh_time_model()
    eigh_full_s = sum(eigh_t(r, d) for r, d in inputs['buckets'])

    out = {}
    # the fourth entry is the COMPUTE-BOUND FLOOR: bytes ignored at the
    # central MXU efficiency. The CPU-derived 'bytes accessed' proxy
    # OVERSTATES TPU HBM traffic (pre-fusion buffer counting, f32-
    # emulated bf16), which makes the three roofline scenarios skew
    # SLOW — so together they bracket the truth from both sides: the
    # chip cannot beat the floor, and should beat the bytes-heavy
    # scenarios if XLA's TPU fusion behaves as designed.
    cases = dict(SCENARIOS)
    cases['central_flops_only'] = (SCENARIOS['central'][0], None)
    for name, (eff, hbm) in cases.items():

        def t(phase, rate=PEAK_BF16, _eff=eff, _hbm=hbm):
            f, b = ph[phase]
            if _hbm is None:
                b = 0.0
            return _phase_time(f, b, _eff, _hbm or 1.0, rate)

        f32 = PEAK_BF16 / F32_PENALTY
        model = t('model')
        prec = t('precondition', f32)
        prec_e = t('precondition_eigen', f32)
        fac = t('factor')
        # the fused capture rung: same GEMM FLOPs, the HBM side scaled
        # by the no-patch-matrix/folded-EMA factor (capture_impl prior)
        fac_f, fac_b = ph['factor']
        fac_pallas = _phase_time(
            fac_f, 0.0 if hbm is None
            else fac_b * CAPTURE_FUSION_BYTES_FACTOR, eff, hbm or 1.0)
        chol = t('inverse_chol', f32)
        refresh = t('refresh', f32)
        scales = t('ekfac_scales', f32)
        sub = t('inverse_subspace', f32)
        ns = t('inverse_ns', f32)

        variants = {
            'sgd': model,
            # factor + inverse every step (headline / anchor cadence)
            'inverse_dp_freq1': model + prec + fac + chol,
            # factor + inverse every 10th step, amortized steady state
            'inverse_dp_freq10': model + prec + (fac + chol) / 10.0,
            # the reference default on TPU: full QDWH eigh every 10th
            # step — the fenced-eigh term dominates everything else
            'eigen_dp_freq10_cold': (model + prec_e
                                     + (fac + eigh_full_s) / 10.0),
            # full eigh 1-in-100 steps, eigenvalue-only refresh at the
            # other 9-in-100 inverse updates
            'eigen_dp_freq10_basis100': (model + prec_e + fac / 10.0
                                         + eigh_full_s / 100.0
                                         + refresh * 9.0 / 100.0),
            # ekfac: scale update every factor step + amortized basis
            'ekfac_freq10_basis100': (model + prec_e
                                      + (fac + scales) / 10.0
                                      + eigh_full_s / 100.0
                                      + refresh * 9.0 / 100.0),
        }
        out[name] = {
            k: {'iter_s': round(v, 4), 'imgs_per_s': round(BATCH / v, 1),
                'vs_baseline': round((BATCH / v)
                                     / (BATCH / BASELINE_ITER_S), 2)}
            for k, v in variants.items()
        }
        out[name]['phases_s'] = {
            'Model': round(model, 4), 'Precondition': round(prec, 4),
            'ComputeFactor': round(fac, 4),
            # the fused capture rung (ops/pallas_capture, ISSUE 19):
            # what the capture_impl knob buys on the modeled chip
            'ComputeFactor_pallas': round(fac_pallas, 4),
            'ComputeInverse_chol': round(chol, 4),
            'ComputeInverse_eigh_full': round(eigh_full_s, 2),
            # the inverse-free ladder rungs (warm kernels, GEMM
            # roofline at the f32 rate — what the decomp_impl knob
            # buys on the modeled chip vs the fenced QDWH seconds)
            'ComputeInverse_subspace': round(sub, 6),
            'ComputeInverse_ns': round(ns, 6),
            'EigenRefresh': round(refresh, 4),
            'EkfacScales': round(scales, 4),
        }
    return out


def prior_phase_costs(block, variant='inverse_dp', anchor='central',
                      decomp_impl=None):
    """Per-phase prior seconds for the autotuner's pre-measurement
    seeding (``autotune.prior_best_freq``): pull the ``anchor``
    scenario's phase predictions out of a ``predict_block()`` dict and
    bind the decomposition phase to the variant's kernel (the fenced
    full eigh for eigen/ekfac, the analytic Cholesky otherwise —
    the same binding ``obs.drift._predicted_phase`` uses). An iterative
    ``decomp_impl`` rebinds to its GEMM-roofline rung, so the freq
    prior prices the kernel the run will actually execute. Returns
    ``{'model', 'precondition', 'factor', 'decomp'}`` seconds, or ``{}``
    when the block carries no usable phases (the tuner then starts from
    the configured cadence instead of a prior)."""
    try:
        ph = block['scenarios'][anchor]['phases_s']
    except (KeyError, TypeError):
        return {}
    eigen = str(variant).startswith(('eigen', 'ekfac'))
    decomp_key = ('ComputeInverse_eigh_full' if eigen
                  else 'ComputeInverse_chol')
    if decomp_impl in ('subspace', 'jacobi', 'auto') and eigen:
        decomp_key = 'ComputeInverse_subspace'
    elif decomp_impl in ('newton_schulz', 'auto') and not eigen:
        decomp_key = 'ComputeInverse_ns'
    out = {
        'model': ph.get('Model'),
        'precondition': ph.get('Precondition'),
        'factor': ph.get('ComputeFactor'),
        'decomp': ph.get(decomp_key),
    }
    if any(v is None for v in out.values()):
        return {}
    return {k: float(v) for k, v in out.items()}


def predict_block(inputs=None):
    """The self-describing block bench.py embeds in its JSON extras."""
    try:
        inputs = inputs or load_inputs()
        c, p, _ = eigh_time_model()
        return {
            'predicted_not_measured': True,
            'method': ('per-phase analytic model: XLA cost_analysis '
                       'FLOPs/bytes (CPU-derived, backend-independent '
                       'dot/conv counts) x roofline scenarios + fenced '
                       'r2 chip constants for the eigh phase; see '
                       'kfac_pytorch_tpu/perfmodel.py'),
            'anchor': {'reference_kfac_iter_s': BASELINE_ITER_S,
                       'source': 'reference scripts/time_breakdown.py:26 '
                                 '(1 GPU, bs 32, factor+inverse every '
                                 'step)'},
            'chip': {'kind': 'TPU v5e (v5 lite)', 'peak_bf16': PEAK_BF16,
                     'hbm_bw': HBM_BW},
            'assumptions': {
                'scenarios_mxu_hbm_eff': {k: list(v) for k, v
                                          in SCENARIOS.items()},
                'f32_gemm_rate': f'peak_bf16 / {F32_PENALTY}',
                'eigh_fit': {'form': 't = c * rows * dim^p',
                             'c': c, 'p': round(p, 4),
                             'fenced_points': [list(x) for x
                                               in FENCED_EIGH_POINTS],
                             'note': 'extrapolated beyond dim 2304 '
                                     '(largest ResNet-50 bucket 4608)'},
                'cholesky_flops': '7/3 d^3 per matrix (analytic; LAPACK '
                                  'custom calls carry no XLA flop count)',
                'iterative_decomp_flops': (
                    'subspace ~(12.7*steps+3) d^3, newton_schulz '
                    '~(4*iters+2) d^3 per matrix at the defaults '
                    '(steps=iters=2) — pure GEMMs, rooflined at the '
                    'f32 rate; the decomp_impl ladder priors '
                    '(ops/linalg.py kernels, autotune seeding)'),
                'bytes_proxy_bias': (
                    'the CPU-derived bytes-accessed totals overstate TPU '
                    'HBM traffic (pre-fusion buffer counting, f32-'
                    'emulated bf16), so the roofline scenarios skew '
                    'SLOW; central_flops_only is the compute-bound '
                    'floor from the other side'),
                'skinny_floor_datapoint': FENCED_ATTN_NOTE,
            },
            'inputs_meta': inputs['meta'],
            'scenarios': (scen := predict(inputs)),
            'headline': {
                'metric': 'predicted_inverse_dp_freq1_imgs_per_s_central',
                'value': scen['central']['inverse_dp_freq1']['imgs_per_s'],
                'falsify': ('a fenced measured value outside the '
                            '[conservative, optimistic] band falsifies '
                            'the model'),
            },
        }
    except Exception as e:  # noqa: BLE001 — bench must still emit
        return {'predicted_not_measured': True,
                'error': f'{type(e).__name__}: {e}'}


def comm_scenarios(per_axis_volume, axis_bw=None, dcn_axes=()):
    """Per-axis K-FAC communication time scenarios for a composed mesh.

    ``per_axis_volume`` is the dict returned by
    ``meshplan.MeshFactorPlan.comm_volume()``: axis name -> phase-bytes
    dict ({'FactorComm': ..., 'InverseComm': ..., 'PredComm': ...}).
    Each axis is priced independently at ``bytes / (eff * bw)`` under
    the COMM_SCENARIOS link-efficiency ladder — the per-axis collectives
    are disjoint device groups, but XLA serialises them within one step,
    so the per-step total is the SUM over axes, not the max.

    ``axis_bw`` optionally overrides the wire rate per axis (B/s);
    axes listed in ``dcn_axes`` default to DCN_BW instead of ICI_BW
    (e.g. a cross-slice data axis). Zero-byte axes (expert, pipeline)
    stay in the output at 0.0 s — the zero-comm claim priced, not
    elided.

    Predicted, not measured: the byte counts are compiler-verified by
    scripts/comm_count.py; only the wire rates here are assumptions.
    """
    axis_bw = dict(axis_bw or {})
    out = {}
    for scen, eff in COMM_SCENARIOS.items():
        axes = {}
        total_s = 0.0
        for ax, phases in per_axis_volume.items():
            bw = axis_bw.get(ax, DCN_BW if ax in dcn_axes else ICI_BW)
            byts = int(sum(phases.values()))
            t = byts / (eff * bw)
            axes[ax] = {'bytes': byts,
                        'phase_bytes': dict(phases),
                        'bw_assumed': bw,
                        's': t}
            total_s += t
        out[scen] = {'axes': axes, 'total_s': total_s}
    return out


def comm_block(per_axis_volume, axis_bw=None, dcn_axes=()):
    """Self-describing wrapper around :func:`comm_scenarios`."""
    return {
        'predicted_not_measured': True,
        'method': ('per-axis serial sum of bytes/(eff*bw); bytes from '
                   'meshplan.MeshFactorPlan.comm_volume (pinned byte-'
                   'for-byte against compiled HLO by '
                   'scripts/comm_count.py composed-mesh specs)'),
        'assumptions': {
            'ici_bw_B_per_s': ICI_BW,
            'dcn_bw_B_per_s': DCN_BW,
            'link_eff_scenarios': dict(COMM_SCENARIOS),
            'serialisation': 'axes summed (XLA serialises same-step '
                             'collectives), intra-axis perfectly '
                             'overlapped within each phase',
        },
        'scenarios': comm_scenarios(per_axis_volume, axis_bw=axis_bw,
                                    dcn_axes=dcn_axes),
    }
