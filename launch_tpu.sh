#!/bin/bash
# TPU launcher — replaces the reference's mpirun/hostfile and ssh/torchrun
# launchers (launch_horovod.sh:32, launch_torch.sh:26-45).
#
# On TPU there is ONE python process per host; intra-host chips are just
# devices in the jax mesh, and multi-host pods coordinate through
# jax.distributed.initialize (driven by TPU runtime env vars — no ssh
# loops, no hostfiles). Single host:
#
#   bash launch_tpu.sh examples/cifar10_resnet.py --num-devices 8 ...
#
# Multi-host (run the same command on every worker of the pod slice, e.g.
# via `gcloud compute tpus tpu-vm ssh --worker=all --command=...`):
#
#   JAX_COORDINATOR_ADDRESS=<worker0-ip>:8476 \
#   JAX_NUM_PROCESSES=<n_hosts> JAX_PROCESS_ID=<this host> \
#   bash launch_tpu.sh examples/imagenet_resnet.py ...
#
# kfac_pytorch_tpu initializes jax.distributed automatically when these
# variables are present (see kfac_pytorch_tpu/parallel/mesh.py).

set -e
cd "$(dirname "$0")"
script="$1"; shift

# env defaults + optional mesh preset (pod=N -> configs/podN), the
# reference's `source configs/envs.conf` + hostfile selection
# (launch_horovod.sh:7,32).
[ -f configs/envs.conf ] && . configs/envs.conf
if [ -n "$pod" ]; then
  if [ -f "configs/pod$pod" ]; then
    set -a                 # export everything the preset defines
    . "configs/pod$pod"
    set +a
    # append so the preset wins over any earlier --num-devices default
    # from the train_*.sh param string (argparse last-occurrence-wins)
    set -- "$@" --num-devices "$KFAC_NUM_DEVICES"
  else
    echo "launch_tpu.sh: no such mesh preset configs/pod$pod" >&2
    exit 1
  fi
fi
export JAX_COMPILATION_CACHE_DIR XLA_PYTHON_CLIENT_PREALLOCATE

# Observability: KFAC_TRACE_DIR=<shared dir> turns on structured trace
# spans in every process of the run (trainers AND supervisors each
# write trace-host<i>[-sup].jsonl there — obs/trace.py install_from_env).
# After a run (or an incident), merge the pod's artifacts into one
# clock-aligned timeline:
#   kfac-obs "$KFAC_TRACE_DIR" logs/*.log -o timeline.json
[ -n "$KFAC_TRACE_DIR" ] && export KFAC_TRACE_DIR

# Communication compression: KFAC_COMM_PRECISION=fp32|bf16|int8 sets the
# wire dtype of the K-FAC factor collectives on every trainer of the run
# (the trainers read it as the --kfac-comm-precision default; an explicit
# flag on the command line still wins). bf16 halves, int8 quarters the
# gather payloads; the stats reduce carries an error-feedback residual
# (KFACState.comm_err); the gradient allreduce is NEVER compressed. See
# README "Communication compression" for when int8 is safe.
if [ -n "$KFAC_COMM_PRECISION" ]; then
  case "$KFAC_COMM_PRECISION" in
    fp32|bf16|int8) export KFAC_COMM_PRECISION ;;
    *) echo "launch_tpu.sh: KFAC_COMM_PRECISION must be fp32|bf16|int8," \
            "got '$KFAC_COMM_PRECISION'" >&2; exit 1 ;;
  esac
fi

# Live replanning (README "Live replanning"): KFAC_COMM_MODE=inverse|pred
# overrides the variant's comm mode for every trainer of the run (the
# trainers read it as the --kfac-comm-mode default; an explicit flag
# still wins). 'inverse' gathers decompositions once per refresh,
# 'pred' gathers preconditioned gradients every step; with the
# autotuner on, the other mode is a real probe/commit rung applied
# mid-run via KFAC.replan — this env sets only the STARTING mode.
if [ -n "$KFAC_COMM_MODE" ]; then
  case "$KFAC_COMM_MODE" in
    inverse|pred) export KFAC_COMM_MODE ;;
    *) echo "launch_tpu.sh: KFAC_COMM_MODE must be inverse|pred," \
            "got '$KFAC_COMM_MODE'" >&2; exit 1 ;;
  esac
fi

# Composed meshes (README "K-FAC on composed meshes"): KFAC_MESH is a
# meshplan spec ('dp2xsp4', 'dp4xtp2', ...) the trainers read as the
# --kfac-mesh default — the axis-aware mesh plan derives the K-FAC
# world from its data/sequence axes. Grammar-checked here so a typo
# fails at launch, not after the pod spins up.
if [ -n "$KFAC_MESH" ]; then
  if echo "$KFAC_MESH" | grep -Eq \
      '^(dp|sp|tp|ep|pp)[0-9]+(=[A-Za-z_][A-Za-z0-9_]*)?(x(dp|sp|tp|ep|pp)[0-9]+(=[A-Za-z_][A-Za-z0-9_]*)?)*$'; then
    export KFAC_MESH
  else
    echo "launch_tpu.sh: KFAC_MESH must be an 'x'-separated list of" \
         "dp/sp/tp/ep/pp axis tokens ('dp2xsp4'), got '$KFAC_MESH'" >&2
    exit 1
  fi
fi

# Closed-loop autotuning: KFAC_AUTOTUNE=1 enables the online knob
# controller in every trainer of the run (the trainers read it as the
# --kfac-autotune default; an explicit flag still wins). The controller
# hill-climbs kfac/fac_update_freq and the comm wire dtype from
# measured step times through the single knob arbiter, with drift-band
# vetoes on the modeled workload; decisions land in the run log
# (kfac-obs renders them) and, under KFAC_TRACE_DIR, in
# <dir>/autotune-decisions.jsonl. See README "Closed-loop autotuning".
if [ -n "$KFAC_AUTOTUNE" ]; then
  case "$KFAC_AUTOTUNE" in
    0|1) export KFAC_AUTOTUNE ;;
    *) echo "launch_tpu.sh: KFAC_AUTOTUNE must be 0 or 1," \
            "got '$KFAC_AUTOTUNE'" >&2; exit 1 ;;
  esac
fi

# Decomposition wall (README "Attacking the decomposition wall"):
# KFAC_DECOMP_IMPL selects the decomposition kernel for every trainer of
# the run (the trainers read it as the --kfac-decomp-impl default; an
# explicit flag still wins): xla = cold QDWH eigh / Cholesky;
# subspace|jacobi (eigh variants) / newton_schulz (Cholesky variants)
# are warm iterative GEMM kernels; auto picks the warm kernel per
# variant. An explicit value is also a live autotuner ladder rung.
if [ -n "$KFAC_DECOMP_IMPL" ]; then
  case "$KFAC_DECOMP_IMPL" in
    xla|auto|jacobi|subspace|newton_schulz) export KFAC_DECOMP_IMPL ;;
    *) echo "launch_tpu.sh: KFAC_DECOMP_IMPL must be" \
            "xla|auto|jacobi|subspace|newton_schulz," \
            "got '$KFAC_DECOMP_IMPL'" >&2; exit 1 ;;
  esac
fi

# Capture hot path (README "Capture hot path", ISSUE 19):
# KFAC_CAPTURE_IMPL selects the capture kernels for every trainer of
# the run (the trainers read it as the --kfac-capture-impl default; an
# explicit flag still wins): xla = the reference patch-extract + GEMM
# + EMA chain; pallas = the fused Pallas kernels (no HBM patch matrix,
# EMA / wire-quantize folded into the epilogues); auto = the fused
# rung, tuner decides. An explicit value is also a live autotuner
# ladder rung.
if [ -n "$KFAC_CAPTURE_IMPL" ]; then
  case "$KFAC_CAPTURE_IMPL" in
    xla|pallas|auto) export KFAC_CAPTURE_IMPL ;;
    *) echo "launch_tpu.sh: KFAC_CAPTURE_IMPL must be" \
            "xla|pallas|auto," \
            "got '$KFAC_CAPTURE_IMPL'" >&2; exit 1 ;;
  esac
fi

# KFAC_DECOMP_SHARD=1 turns on mesh-sharded decomposition (the
# --kfac-decomp-shard default): each refresh cohort's eigh/inverse rows
# are repartitioned cost-balanced across ALL devices instead of
# owner-local — ~P x shorter decomposition critical path for two
# bounded DecompComm gathers per step (scripts/comm_count.py pins the
# wire bytes against FactorPlan.comm_volume). Implies the staggered
# schedule.
if [ -n "$KFAC_DECOMP_SHARD" ]; then
  case "$KFAC_DECOMP_SHARD" in
    0|1) export KFAC_DECOMP_SHARD ;;
    *) echo "launch_tpu.sh: KFAC_DECOMP_SHARD must be 0 or 1," \
            "got '$KFAC_DECOMP_SHARD'" >&2; exit 1 ;;
  esac
fi

if [ -n "$JAX_COORDINATOR_ADDRESS" ]; then
  export KFAC_TPU_MULTIHOST=1
fi

# Coordination backend (kfac_pytorch_tpu/coord/, README "Coordination
# backends"): where the pod protocols — shrink/grow barrier claims,
# lineage fencing, heartbeat file-leases, join/done markers, the
# kfac-serve queue — keep their state.
#   KFAC_COORD_BACKEND  posix (default: the shared lease DIRECTORY,
#                       byte-compatible protocol files) | tcp (an
#                       etcd-style KV server, no shared filesystem —
#                       run one with `kfac-coord-serve --port 8479`)
#   KFAC_COORD_ADDR     host:port of the KV server (required for tcp)
#   KFAC_COORD_ADDRS    comma-separated host:port of the KV replicas —
#                       normally 3 (required for replicated; one
#                       replica down is invisible, quorum loss exits
#                       RC_COORD_LOST=118)
# Backend fault drills: KFAC_FAULT_COORD_* (seed/fail/torn/stale/cas/
# lease_expire/windows — faults.py STRICT from_env; on replicated they
# arm PER REPLICA with decorrelated seeds).
if [ -n "$KFAC_COORD_BACKEND" ]; then
  case "$KFAC_COORD_BACKEND" in
    posix) export KFAC_COORD_BACKEND ;;
    tcp)
      : "${KFAC_COORD_ADDR:?KFAC_COORD_BACKEND=tcp needs KFAC_COORD_ADDR (host:port of a kfac-coord-serve KV server)}"
      export KFAC_COORD_BACKEND KFAC_COORD_ADDR ;;
    replicated)
      : "${KFAC_COORD_ADDRS:?KFAC_COORD_BACKEND=replicated needs KFAC_COORD_ADDRS (comma-separated host:port of the kfac-coord-serve replicas, normally 3)}"
      case "$KFAC_COORD_ADDRS" in
        *[,\;]*) ;;
        *) echo "launch_tpu.sh: KFAC_COORD_ADDRS needs at least 2" \
                "comma-separated replicas, got '$KFAC_COORD_ADDRS'" \
                >&2; exit 1 ;;
      esac
      export KFAC_COORD_BACKEND KFAC_COORD_ADDRS ;;
    *) echo "launch_tpu.sh: KFAC_COORD_BACKEND must be" \
            "posix|tcp|replicated, got '$KFAC_COORD_BACKEND'" >&2
       exit 1 ;;
  esac
fi

# Training service (kfac-serve, kfac_pytorch_tpu/service/): when this
# launch is one tenant job of the multi-tenant service, the scheduler
# exports the per-job namespace env — pass it through so every child
# (supervisor + trainer) logs, traces and exports metrics into the
# job's own tenant directory instead of a shared path:
#   KFAC_TENANT     tenant name (metrics/prom paths are namespaced by it)
#   KFAC_JOB_ID     job-NNNNNN (ditto)
#   KFAC_PROM_FILE  the job's Prometheus textfile (trainers default
#                   --prom-file to it)
# KFAC_HB_PORT is also service-assigned per job (disjoint blocks), so
# jobs sharing a host never fight over heartbeat responder ports — the
# ${KFAC_HB_PORT:-8478} default below only applies outside the service.
[ -n "$KFAC_TENANT" ] && export KFAC_TENANT
[ -n "$KFAC_JOB_ID" ] && export KFAC_JOB_ID
[ -n "$KFAC_PROM_FILE" ] && export KFAC_PROM_FILE

# Peer-heartbeat transport (KFAC_HB_*, resilience/heartbeat.py).
# Contract consumed by heartbeat_from_env in every trainer:
#   KFAC_HB_TRANSPORT  file | tcp  (default: tcp when the pod has >1
#                      worker, file otherwise — file leases need a
#                      shared POSIX filesystem, which real multi-host
#                      pods don't have; single-host smoke runs keep the
#                      zero-config lease dir)
#   KFAC_HB_PORT       port each host's TCP responder binds (8478)
#   KFAC_HB_PEERS      "rank=host:port,..." for every rank; derived
#                      below from KFAC_HB_WORKERS="ip0 ip1 ..." (the
#                      pod's worker addresses in rank order) when unset
#   KFAC_HB_HOST/HOSTS this rank / world size (default: the jax pod
#                      coordination env)
#   KFAC_HB_INTERVAL/DEADLINE/GRACE  beat cadence / silence-to-death /
#                      startup grace, seconds
#   KFAC_HB_GEN        pod generation (the pod supervisor re-exports it
#                      per shrink/grow so a rejoined host's restarted
#                      sequence counter is never misread as stale)
nworkers="${JAX_NUM_PROCESSES:-1}"
if [ -z "$KFAC_HB_TRANSPORT" ] && [ "$nworkers" -gt 1 ] \
    && { [ -n "$KFAC_HB_PEERS" ] || [ -n "$KFAC_HB_WORKERS" ]; }; then
  # multi-host with a derivable peer map: tcp is the default transport
  export KFAC_HB_TRANSPORT=tcp
fi
if [ "$KFAC_HB_TRANSPORT" = tcp ]; then
  export KFAC_HB_PORT="${KFAC_HB_PORT:-8478}"
  if [ -z "$KFAC_HB_PEERS" ]; then
    if [ -n "$KFAC_HB_WORKERS" ]; then
      i=0; peers=""
      for w in $KFAC_HB_WORKERS; do
        peers="${peers:+$peers,}$i=$w:$KFAC_HB_PORT"
        i=$((i+1))
      done
      export KFAC_HB_PEERS="$peers"
    else
      # tcp was asked for EXPLICITLY but the peer map is underivable —
      # fail loudly rather than run a pod whose hosts can't see each
      # other die
      echo "launch_tpu.sh: KFAC_HB_TRANSPORT=tcp needs KFAC_HB_PEERS" \
           "(rank=host:port,...) or KFAC_HB_WORKERS (\"ip0 ip1 ...\")" >&2
      exit 1
    fi
  fi
  export KFAC_HB_HOST="${KFAC_HB_HOST:-${JAX_PROCESS_ID:-0}}"
  export KFAC_HB_HOSTS="${KFAC_HB_HOSTS:-$nworkers}"
fi

# Central env contract (kfac_pytorch_tpu/envspec.py; README "Static
# analysis"): every exported KFAC_* name must be declared in the
# registry and carry a well-formed value. A typo'd knob
# (KFAC_COMM_PRECISON=bf16) kills the launch here, in milliseconds,
# instead of silently never arming on an allocated pod. envspec.py is
# stdlib-pure and run as a bare file, so this works on hosts where jax
# itself is broken — the value checks above stay as the launcher's own
# fast path; the registry is the completeness net (undeclared names,
# malformed values of everything else).
if ! "${PY:-python}" kfac_pytorch_tpu/envspec.py --validate; then
  echo "launch_tpu.sh: environment failed the envspec contract (above)" >&2
  exit 1
fi

# Pod-resilience wrapper: KFAC_POD_SUPERVISE=1 runs the trainer under
# the per-host kfac-pod-supervise loop (resilience/elastic.py) — on top
# of the crash/hang restarts below, the supervisors heartbeat each other
# through KFAC_POD_LEASE_DIR (a shared directory every host can see);
# a host that dies for good (trainer rc 115 RC_PEER_DEAD, or this
# supervisor's own monitor) triggers the shrink protocol: the survivors
# agree on the surviving set, relaunch at the reduced world size, and
# the trainers reshard their K-FAC factor state through elastic_resume.
# An incident report JSON lands in the lease dir on every exit path.
# Requires JAX_PROCESS_ID / JAX_NUM_PROCESSES (the pod coordination env
# above) and a checkpoint dir, like KFAC_SUPERVISE.
# Rejoin after repair: KFAC_POD_JOIN=1 on the REPAIRED host announces
# it on the heartbeat channel instead of cold-launching; the incumbent
# pod runs the grow barrier, every trainer relaunches at the enlarged
# world, and factor state reshards UP through elastic_resume. Exit 116
# (join_failed) means the pod never answered within KFAC_JOIN_TIMEOUT.
# Partitions: membership changes are QUORUM-GATED — the minority side
# of a network partition exits 117 (fenced) instead of relaunching a
# rival generation, stops finalizing checkpoints, and rejoins via
# KFAC_POD_JOIN=1 once the network heals; the supervisor exports the
# lineage epoch as KFAC_LINEAGE so a fenced fork's state is refused at
# resume. Drill it deterministically with the KFAC_FAULT_NET_* network
# chaos env (seeded drop/delay/dup/reorder + a time-windowed partition
# matrix; see resilience/chaos_net.py and README "Network partitions")
# — inherited by the supervisors and trainers like every KFAC_FAULT_*.
if [ -n "$KFAC_POD_SUPERVISE" ]; then
  : "${KFAC_POD_LEASE_DIR:?KFAC_POD_SUPERVISE=1 needs KFAC_POD_LEASE_DIR (shared across hosts)}"
  exec "${PY:-python}" -m kfac_pytorch_tpu.resilience.elastic \
    --host-id "${JAX_PROCESS_ID:-0}" \
    --num-hosts "${JAX_NUM_PROCESSES:-1}" \
    --lease-dir "$KFAC_POD_LEASE_DIR" \
    ${KFAC_HOST_ADDR:+--host-addr "$KFAC_HOST_ADDR"} \
    ${KFAC_POD_JOIN:+--join} \
    ${KFAC_JOIN_TIMEOUT:+--join-timeout "$KFAC_JOIN_TIMEOUT"} \
    --max-restarts "${KFAC_MAX_RESTARTS:-3}" \
    --backoff-base "${KFAC_RESTART_BACKOFF:-2}" \
    --hb-interval "${KFAC_HB_INTERVAL:-2}" \
    --hb-deadline "${KFAC_HB_DEADLINE:-10}" \
    -- "${PY:-python}" "$script" "$@"
fi

# Resilient-runtime wrapper: KFAC_SUPERVISE=1 runs the trainer under the
# kfac-supervise restart loop (kfac_pytorch_tpu/resilience/supervisor.py)
# — a crash (nonzero rc / signal death) or a step-watchdog hang abort
# (rc 114) relaunches the trainer up to KFAC_MAX_RESTARTS times with
# exponential backoff; the trainer resumes via its auto_resume
# checkpoint path. Give the trainer a --checkpoint-dir/--resume (cifar)
# or --checkpoint-format (imagenet, always on) or restarts start over.
# KFAC_STOP_RCS ("peer_dead 7 ...") propagates those exit codes instead
# of restarting — names from the protocol table (README) or numbers.
if [ -n "$KFAC_SUPERVISE" ]; then
  stop_rc_flags=""
  for rc in ${KFAC_STOP_RCS:-}; do
    stop_rc_flags="$stop_rc_flags --stop-rc $rc"
  done
  exec "${PY:-python}" -m kfac_pytorch_tpu.resilience.supervisor \
    --max-restarts "${KFAC_MAX_RESTARTS:-3}" \
    --backoff-base "${KFAC_RESTART_BACKOFF:-2}" \
    $stop_rc_flags \
    -- "${PY:-python}" "$script" "$@"
fi

exec "${PY:-python}" "$script" "$@"
