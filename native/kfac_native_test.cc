// Standalone native-layer test (the analogue of the reference's
// packages/tcmm/tests/main.cpp smoke binaries). Exits nonzero on failure.

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {
double block_partition(const double*, int64_t, int64_t, int64_t*);
double lpt_assign(const double*, int64_t, int64_t, int64_t*);
void augment_crop_flip(const float*, int64_t, int64_t, int64_t, int64_t,
                       int64_t, const int32_t*, const uint8_t*, float*);
}

int main() {
  // block partition: [5,1,1,1,5] into 3 -> bottleneck 5
  std::vector<double> costs = {5, 1, 1, 1, 5};
  std::vector<int64_t> owners(5);
  double b = block_partition(costs.data(), 5, 3, owners.data());
  assert(b == 5.0);
  assert(owners[0] == 0 && owners[4] == 2);

  // LPT: [4,3,3,2] on 2 devices -> makespan 6
  std::vector<double> c2 = {4, 3, 3, 2};
  std::vector<int64_t> o2(4);
  double m = lpt_assign(c2.data(), 4, 2, o2.data());
  assert(m == 6.0);

  // augmentation: zero offset+pad reproduces identity; flip reverses
  const int64_t n = 1, h = 4, w = 4, cch = 2;
  std::vector<float> img(h * w * cch);
  for (size_t i = 0; i < img.size(); ++i) img[i] = float(i);
  std::vector<int32_t> offs = {4, 4};  // center crop of pad-4 == identity
  std::vector<uint8_t> flips = {0};
  std::vector<float> out(img.size());
  augment_crop_flip(img.data(), n, h, w, cch, 4, offs.data(), flips.data(),
                    out.data());
  for (size_t i = 0; i < img.size(); ++i) assert(out[i] == img[i]);
  flips[0] = 1;
  augment_crop_flip(img.data(), n, h, w, cch, 4, offs.data(), flips.data(),
                    out.data());
  for (int64_t y = 0; y < h; ++y)
    for (int64_t x = 0; x < w; ++x)
      for (int64_t ch = 0; ch < cch; ++ch)
        assert(out[(y * w + x) * cch + ch] ==
               img[(y * w + (w - 1 - x)) * cch + ch]);

  std::printf("kfac_native_test: all checks passed\n");
  return 0;
}
