// kfac_native: host-side native runtime components.
//
// The reference's native layer (packages/tcmm: cuSOLVER eig, cuBLAS GEMM,
// NCCL+MPI communicator) maps almost entirely onto on-chip XLA ops and
// ICI collectives in this framework (see SURVEY.md §2.2). What remains
// host-side — and is worth native code — is:
//
//  1. the factor-work scheduler: optimal contiguous bottleneck partition
//     (dynamic programming, O(P·N²); reference research code:
//     scripts/dp_block_partition.py:11-76) and LPT greedy assignment,
//     called at plan-build time for large layer counts;
//  2. the input-pipeline augmentation kernel: batched pad-4 random crop +
//     horizontal flip (the reference's torchvision transform stack,
//     examples/pytorch_cifar10_resnet.py:157-163), which in Python costs a
//     per-image interpreter loop on the host data path.
//
// Exposed with plain C linkage for ctypes (no pybind11 in this image).
//
// Build: cc -O2 -shared -fPIC -o libkfac_native.so kfac_native.cc
// (or the CMakeLists.txt alongside).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

extern "C" {

// Optimal contiguous bottleneck partition of `costs[0..n)` into `p`
// blocks; writes block owner per item into `owners`. Returns the
// bottleneck cost.
double block_partition(const double* costs, int64_t n, int64_t p,
                       int64_t* owners) {
  if (n == 0) return 0.0;
  int64_t k = std::min<int64_t>(p, n);
  std::vector<double> prefix(n + 1, 0.0);
  for (int64_t i = 0; i < n; ++i) prefix[i + 1] = prefix[i] + costs[i];
  const double inf = std::numeric_limits<double>::infinity();
  std::vector<double> dp((k + 1) * (n + 1), inf);
  std::vector<int64_t> cut((k + 1) * (n + 1), 0);
  dp[0] = 0.0;
  for (int64_t b = 1; b <= k; ++b) {
    for (int64_t i = 1; i <= n; ++i) {
      for (int64_t j = b - 1; j < i; ++j) {
        double cand = std::max(dp[(b - 1) * (n + 1) + j],
                               prefix[i] - prefix[j]);
        if (cand < dp[b * (n + 1) + i]) {
          dp[b * (n + 1) + i] = cand;
          cut[b * (n + 1) + i] = j;
        }
      }
    }
  }
  int64_t i = n;
  for (int64_t b = k; b >= 1; --b) {
    int64_t j = cut[b * (n + 1) + i];
    for (int64_t t = j; t < i; ++t) owners[t] = b - 1;
    i = j;
  }
  return dp[k * (n + 1) + n];
}

// Greedy longest-processing-time assignment (order-free balanced
// scheduler). Writes owner per item; returns the makespan.
double lpt_assign(const double* costs, int64_t n, int64_t p,
                  int64_t* owners) {
  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return costs[a] > costs[b]; });
  std::vector<double> load(p, 0.0);
  for (int64_t idx : order) {
    int64_t best = 0;
    for (int64_t d = 1; d < p; ++d)
      if (load[d] < load[best]) best = d;
    owners[idx] = best;
    load[best] += costs[idx];
  }
  return *std::max_element(load.begin(), load.end());
}

// Batched pad-4 reflect crop + horizontal flip for [N, H, W, C] float32
// images. offs: [N, 2] crop offsets in [0, 2*pad]; flips: [N] 0/1.
void augment_crop_flip(const float* x, int64_t n, int64_t h, int64_t w,
                       int64_t c, int64_t pad, const int32_t* offs,
                       const uint8_t* flips, float* out) {
  const int64_t hp = h + 2 * pad, wp = w + 2 * pad;
  std::vector<float> padded(hp * wp * c);
  for (int64_t i = 0; i < n; ++i) {
    const float* img = x + i * h * w * c;
    // reflect pad
    for (int64_t y = 0; y < hp; ++y) {
      int64_t sy = y - pad;
      if (sy < 0) sy = -sy;
      if (sy >= h) sy = 2 * h - 2 - sy;
      for (int64_t xx = 0; xx < wp; ++xx) {
        int64_t sx = xx - pad;
        if (sx < 0) sx = -sx;
        if (sx >= w) sx = 2 * w - 2 - sx;
        std::memcpy(&padded[(y * wp + xx) * c], &img[(sy * w + sx) * c],
                    c * sizeof(float));
      }
    }
    const int64_t oy = offs[2 * i], ox = offs[2 * i + 1];
    float* dst = out + i * h * w * c;
    for (int64_t y = 0; y < h; ++y) {
      const float* row = &padded[((y + oy) * wp + ox) * c];
      if (flips[i]) {
        for (int64_t xx = 0; xx < w; ++xx)
          std::memcpy(&dst[(y * w + xx) * c], &row[(w - 1 - xx) * c],
                      c * sizeof(float));
      } else {
        std::memcpy(&dst[y * w * c], row, w * c * sizeof(float));
      }
    }
  }
}

}  // extern "C"
