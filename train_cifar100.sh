#!/bin/bash
# CIFAR-100 driver (reference parity: train_cifar100.sh — VGG-16 default).

dnn="${dnn:-vgg16}"
batch_size="${batch_size:-128}"
base_lr="${base_lr:-0.1}"
epochs="${epochs:-100}"
kfac="${kfac:-1}"
fac="${fac:-1}"
kfac_name="${kfac_name:-eigen_dp}"
basis_freq="${basis_freq:-0}"        # full-eigh cadence (0 = every inverse update)
damping="${damping:-0.03}"
nworkers="${nworkers:-1}"

params="--dataset cifar100 --model $dnn --batch-size $batch_size \
  --base-lr $base_lr --epochs $epochs --kfac-update-freq $kfac \
  --kfac-cov-update-freq $fac --kfac-name $kfac_name --kfac-basis-update-freq $basis_freq --damping $damping \
  --num-devices $nworkers"
[ -n "$data_dir" ] && params="$params --dir $data_dir"

bash "$(dirname "$0")/launch_tpu.sh" examples/cifar10_resnet.py $params "$@"
