#!/bin/bash
# ImageNet driver — reference parity (train_imagenet.sh:4-27): 55-epoch
# K-FAC schedule replacing the 90-epoch SGD schedule.

dnn="${dnn:-resnet50}"
batch_size="${batch_size:-32}"
base_lr="${base_lr:-0.0125}"
epochs="${epochs:-55}"
if [ "$epochs" = "90" ]; then
  lr_decay="${lr_decay:-30 60 80}"
else
  lr_decay="${lr_decay:-25 35 40 45 50}"
fi
kfac="${kfac:-1}"
fac="${fac:-1}"
kfac_name="${kfac_name:-eigen_dp}"
basis_freq="${basis_freq:-0}"        # full-eigh cadence (0 = every inverse update)
stat_decay="${stat_decay:-0.95}"
damping="${damping:-0.002}"
exclude_parts="${exclude_parts:-}"
nworkers="${nworkers:-1}"

params="--model $dnn --batch-size $batch_size --base-lr $base_lr \
  --epochs $epochs --lr-decay $lr_decay --kfac-update-freq $kfac \
  --kfac-cov-update-freq $fac --kfac-name $kfac_name --kfac-basis-update-freq $basis_freq \
  --stat-decay $stat_decay --damping $damping --num-devices $nworkers"
[ -n "$exclude_parts" ] && params="$params --exclude-parts $exclude_parts"
[ -n "$train_dir" ] && params="$params --train-dir $train_dir"

bash "$(dirname "$0")/launch_tpu.sh" examples/imagenet_resnet.py $params "$@"
