#!/bin/bash
# Experiment batcher — the reference's convergence/efficiency preset runner
# (batch.sh:26-32), one line per workload at its published configuration.
# Usage: bash batch.sh [efficiency|convergence]

mode="${1:-efficiency}"
cd "$(dirname "$0")"

if [ "$mode" = "efficiency" ]; then
  # speed presets (reference batch.sh:26-32)
  dnn=resnet110 batch_size=128 nworkers=4 bash train_cifar10.sh --speed
  dnn=vgg16 batch_size=128 nworkers=4 bash train_cifar100.sh --speed
  dnn=resnet50 batch_size=32 nworkers=8 bash train_imagenet.sh --speed
  dnn=inceptionv4 batch_size=16 nworkers=8 bash train_imagenet.sh --speed
  batch_size=128 nworkers=8 bash train_multi30k.sh --speed
  batch_size=4 nworkers=8 bash train_squad.sh
else
  # convergence presets
  dnn=resnet110 bash train_cifar10.sh
  dnn=vgg16 bash train_cifar100.sh
  dnn=resnet50 bash train_imagenet.sh
  bash train_multi30k.sh
  bash train_squad.sh
fi
