#!/bin/bash
# SQuAD BERT driver (reference parity: train_squad.sh).

model_size="${model_size:-base}"
batch_size="${batch_size:-4}"
epochs="${epochs:-2}"
base_lr="${base_lr:-0.04}"
kfac="${kfac:-1}"
fac="${fac:-1}"
kfac_name="${kfac_name:-eigen_dp}"
basis_freq="${basis_freq:-0}"        # full-eigh cadence (0 = every inverse update)
damping="${damping:-0.003}"
nworkers="${nworkers:-1}"

params="--model-size $model_size --batch-size $batch_size \
  --epochs $epochs --base-lr $base_lr --kfac-update-freq $kfac \
  --kfac-cov-update-freq $fac --kfac-name $kfac_name --kfac-basis-update-freq $basis_freq --damping $damping \
  --num-devices $nworkers"
[ -n "$train_file" ] && params="$params --train-file $train_file"

bash "$(dirname "$0")/launch_tpu.sh" examples/squad_bert.py $params "$@"
