#!/bin/bash
# CIFAR-10 driver — env-var-parameterized defaults, same knob surface as
# the reference (train_cifar10.sh:4-27). kfac=0 => pure SGD baseline.

dnn="${dnn:-resnet32}"
batch_size="${batch_size:-128}"
base_lr="${base_lr:-0.1}"
epochs="${epochs:-100}"
kfac="${kfac:-1}"                 # kfac_update_freq (0 disables)
fac="${fac:-1}"                   # fac (cov) update freq
kfac_name="${kfac_name:-eigen_dp}"
basis_freq="${basis_freq:-0}"        # full-eigh cadence (0 = every inverse update)
stat_decay="${stat_decay:-0.95}"
damping="${damping:-0.03}"
kl_clip="${kl_clip:-0.001}"
exclude_parts="${exclude_parts:-}"
lr_decay="${lr_decay:-35 75 90}"
nworkers="${nworkers:-1}"         # devices in the mesh
data_dir="${data_dir:-}"

params="--model $dnn --batch-size $batch_size --base-lr $base_lr \
  --epochs $epochs --kfac-update-freq $kfac --kfac-cov-update-freq $fac \
  --kfac-name $kfac_name --kfac-basis-update-freq $basis_freq --stat-decay $stat_decay --damping $damping \
  --kl-clip $kl_clip --lr-decay $lr_decay --num-devices $nworkers"
[ -n "$exclude_parts" ] && params="$params --exclude-parts $exclude_parts"
[ -n "$data_dir" ] && params="$params --dir $data_dir"

bash "$(dirname "$0")/launch_tpu.sh" examples/cifar10_resnet.py $params "$@"
