"""Shared helpers for the research/benchmark scripts.

Capability parity with the reference's script helpers
(reference: scripts/utils.py:1-112 — shared log-parsing/plot utilities for
the offline analysis scripts). Here: platform forcing (the virtual-CPU-mesh
escape hatch), timing, and linear cost-model fitting.
"""

import os
import time


def force_platform():
    """Honor KFAC_PLATFORM / KFAC_HOST_DEVICES before any JAX client exists.

    The driver environment pins ``JAX_PLATFORMS`` at interpreter start, so
    scripts offer their own escape hatch to run distributed probes on a
    virtual CPU mesh::

        KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=8 python scripts/test_collectives.py

    Must be called before any ``jax.devices()`` / computation.
    """
    plat = os.environ.get('KFAC_PLATFORM')
    if not plat:
        return
    from kfac_pytorch_tpu.utils.platform import force_host_platform
    force_host_platform(plat, int(os.environ.get('KFAC_HOST_DEVICES', '8')))


# --model flag values (models/__init__.py registry) that are ImageNet-scale;
# everything else in the zoo is CIFAR-scale (32x32, 10/100 classes).
IMAGENET_MODELS = frozenset({
    'resnet18', 'resnet34', 'resnet50', 'resnet101', 'resnet152',
    'resnext50', 'resnext101', 'inceptionv4', 'inception-v4'})


def build_vision_model(name, img=None, num_classes=None):
    """Resolve a ``--model`` flag to (model, img_size, num_classes) through
    the zoo registry (same name surface as the example entrypoints)."""
    from kfac_pytorch_tpu import models
    if name in IMAGENET_MODELS:
        img = img or (299 if 'inception' in name else 224)
        num_classes = num_classes or 1000
    else:
        img = img or 32
        num_classes = num_classes or 10
    return models.get_model(name, num_classes=num_classes), img, num_classes


def timeit(fn, *args, warmup=2, iters=10, vary=None):
    """Mean wall-clock seconds per call, synchronized by a host fetch of
    the last output (``kfac_pytorch_tpu.utils.profiling.host_fence`` —
    ``jax.block_until_ready`` does not fence execution on the tunneled
    TPU platform).

    vary: optional ``vary(i) -> args`` callable producing per-iteration
    inputs — repeated identical (program, inputs) executions can be
    served from caches on remote platforms, so A/B microbenches should
    pass distinct inputs per iteration.
    """
    from kfac_pytorch_tpu.utils.profiling import host_fence
    for i in range(warmup):
        out = fn(*(vary(i) if vary else args))
    host_fence(out)
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(*(vary(warmup + i) if vary else args))
    host_fence(out)
    return (time.perf_counter() - t0) / iters


def fit_linear(xs, ys):
    """Least-squares fit of ``y = alpha + beta * x`` (the alpha-beta
    latency/bandwidth model, reference scripts/comm_models.py:8-19)."""
    import numpy as np
    X = np.stack([np.ones(len(xs)), np.asarray(xs, float)], axis=1)
    (alpha, beta), *_ = np.linalg.lstsq(X, np.asarray(ys), rcond=None)
    return float(alpha), float(beta)
