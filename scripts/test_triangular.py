"""Triangular-structure semantics probe for the Cholesky-inverse path.

Capability parity with the reference's triangular probe
(reference: scripts/test_triangular.py:1-24 — checks the
lower-triangular copy/transpose identity used by its Cholesky inverse,
kfac/utils.py:14-16). Validates the identities the TPU `psd_inverse`
relies on:

  1. cholesky(X) returns lower-triangular L with L @ L.T == X;
  2. reconstructing the full symmetric inverse from the triangular solve
     equals the dense inverse;
  3. tril/triu extraction and symmetrization round-trips.

Usage: python scripts/test_triangular.py [--dim 512]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import ops


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--dim', type=int, default=512)
    args = p.parse_args()
    d = args.dim

    rng = np.random.RandomState(0)
    a = rng.randn(d, d).astype(np.float32) / np.sqrt(d)
    x = jnp.asarray(a @ a.T + np.eye(d, dtype=np.float32))

    # 1. cholesky is lower triangular and reconstructs x
    L = jnp.linalg.cholesky(x)
    assert float(jnp.abs(jnp.triu(L, 1)).max()) == 0.0
    err = float(jnp.abs(L @ L.T - x).max() / jnp.abs(x).max())
    print(f'cholesky reconstruction rel err: {err:.2e}')
    assert err < 1e-4

    # 2. psd_inverse == dense inverse
    inv = ops.psd_inverse(x)
    ref = jnp.linalg.inv(x)
    err = float(jnp.abs(inv - ref).max() / jnp.abs(ref).max())
    print(f'psd_inverse vs dense inverse rel err: {err:.2e}')
    assert err < 1e-2

    # 3. symmetrization round-trip: tril + strict-tril^T rebuilds symmetric
    sym = jnp.tril(inv) + jnp.tril(inv, -1).T
    err = float(jnp.abs(sym - inv).max())
    print(f'tril symmetrization max err: {err:.2e}')
    assert err < 1e-4

    print('ok')


if __name__ == '__main__':
    main()
