"""RESOLVED case study (round 3): an apparent MPD-'eigen' nd>=2
divergence under an ORTHOGONAL varying mesh axis ('expert') that was NOT
an engine bug. Kept as a postmortem because both failure modes are easy
to hit again:

1. The K-FAC capture convention is a LOCAL-mean loss. A globally
   psum-normalized loss leaves grads and A factors equal but makes the
   engine's G-factor scale shard-size-dependent (local cotangents x
   local-batch scaling), so cross-mesh comparisons diverge in exactly
   the preconditioned output while every input looks equal.
2. `check_vma=False` on a shard_map disables vma autodiff's AUTOMATIC
   cross-axis gradient psum — debug probes taken under it show grads
   missing their reductions and will send the investigation sideways.

With the convention respected the full nd=2 cross-mesh invariance
passes: tests/test_moe.py::test_moe_kfac_dp_ep_invariance.

Usage: [NOKL=1] [VARIANT=eigen|eigen_dp] python scripts/repro_mpd_eigen_orthogonal_axis.py
"""
import sys; sys.path.insert(0, 'tests'); sys.path.insert(0, '.')
print('=' * 72)
print('POSTMORTEM REPRODUCER: the harness below DELIBERATELY commits the')
print('two mistakes the docstring describes (global-psum loss and')
print('check_vma=False probes) — divergent numbers in this output are the')
print('EXPECTED broken-harness signature, NOT an engine bug. The correct-')
print('convention invariance passes in tests/test_moe.py.')
print('=' * 72)
from kfac_pytorch_tpu.utils.platform import force_host_platform
force_host_platform("cpu", 8)
print('importing test_moe', flush=True)
import test_moe as m
print('imported', flush=True)
import numpy as np, jax, jax.numpy as jnp, functools
from jax.sharding import Mesh, PartitionSpec as P
import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture
from kfac_pytorch_tpu.parallel.moe import SwitchMoE
NE2, ND = 2, 2
TL, D, DH = m.TL, m.D, m.DH
T = NE2 * TL
x = jnp.asarray(np.random.RandomState(5).randn(ND*T, D), jnp.float32)
y = jnp.asarray(np.random.RandomState(6).randn(ND*T, D), jnp.float32)
gate, experts, stacked = m._params(11)
gate = {'kernel': gate['kernel'][:, :NE2], 'bias': gate['bias'][:NE2]}
stacked2 = jax.tree.map(lambda a: a[:NE2], stacked)
local = SwitchMoE(D, DH, capacity=T, axis=None)
especs = jax.tree.map(lambda _: P('expert'), stacked2)
params = {'gate': gate, 'expert': stacked2}

def make_pre(nd, axis):
    import os
    KL = None if os.environ.get('NOKL') else 0.001
    import os as _os
    VAR = _os.environ.get('VARIANT', 'eigen')
    pre = kfac.KFAC(variant=VAR, lr=0.1, damping=0.01, kl_clip=KL,
                    fac_update_freq=1, kfac_update_freq=1,
                    num_devices=nd, axis_name=axis)
    xs = x[:T]
    variables = capture.init(local, jax.random.PRNGKey(0), xs)
    pre.setup(capture.collect_layer_meta(local, variables, xs))
    return pre

def run(mesh, axes, kfac_axis, nd, cap):
    moe = SwitchMoE(D, DH, capacity=cap, axis='expert')
    pre = make_pre(nd, kfac_axis)
    kstate = jax.tree.map(lambda a: jnp.stack([a]*NE2), pre.init())
    inner = (pre.state_pspecs(kfac_axis) if kfac_axis
             else jax.tree.map(lambda _: P(), pre.state_pspecs(None)))
    kspecs = jax.tree.map(lambda s: P('expert', *s), inner,
                          is_leaf=lambda v: isinstance(v, P))
    pre1 = make_pre(1, None)
    kstate1 = jax.tree.map(lambda a: jnp.stack([a]*NE2), pre1.init())
    ks1 = jax.tree.map(lambda s: P('expert', *s),
                       jax.tree.map(lambda _: P(), pre1.state_pspecs(None)),
                       is_leaf=lambda v: isinstance(v, P))
    oes = jax.tree.map(lambda _: P('expert'), especs)
    @functools.partial(jax.shard_map, mesh=mesh,
        in_specs=({'gate': P(), 'expert': especs}, kspecs, P(axes), P(axes)),
        out_specs=(especs, especs), check_vma=False)
    def step(params, kstate, x, y):
        kstate1_ = jax.tree.map(lambda a: a, kstate1)
        local_p = {'gate': params['gate'],
                   'expert': jax.tree.map(lambda a: a[0], params['expert'])}
        all_axes = (('data', 'expert') if kfac_axis else 'expert')
        def gm(o):
            s = ((o[0] - y) ** 2).sum() / (ND * T * D)
            return jax.lax.psum(s, all_axes)
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            moe, gm, {'params': local_p}, x, axis_name=all_axes)
        k = jax.tree.map(lambda a: a[0], kstate)
        ng, _ = pre.step(k, grads, acts, gs, axis_name=kfac_axis)
        if kfac_axis:
            # the SAME captures through an nd=1 world-of-one engine: the
            # distributed result must match it exactly
            k1 = jax.tree.map(lambda a: a[0], kstate1)
            ng1, _ = pre1.step(k1, grads, acts, gs, axis_name=None)
        else:
            ng1 = ng
        return (jax.tree.map(lambda a: a[None], ng['expert']),
                jax.tree.map(lambda a: a[None], ng1['expert']))
    return step(params, kstate, x, y)

total = ND * T
mesh_dp = Mesh(np.array(jax.devices()[:ND*NE2]).reshape(ND, NE2), ('data','expert'))
print("running dp+ep (nd=2)...", flush=True)
got = run(mesh_dp, ('data','expert'), 'data', ND, cap=total // (ND*NE2))
mesh_e = Mesh(np.array(jax.devices()[:NE2]), ('expert',))
print("running expert-only...", flush=True)
want = run(mesh_e, 'expert', None, 1, cap=total // NE2)
def flat(t):
    return {jax.tree_util.keystr(p): v
            for p, v in jax.tree_util.tree_leaves_with_path(t)}
gd, g1 = flat(got[0]), flat(got[1])
print('=== nd=2 engine vs in-program nd=1 engine, same captures:')
for kk in gd:
    print(kk, float(np.abs(np.asarray(gd[kk], np.float64)
                           - np.asarray(g1[kk], np.float64)).max()))
import sys; sys.exit(0)
for name, a, b in (('A', got[0], want[0]), ('G', got[1], want[1])):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    print(name, 'shape', a.shape, 'maxdiff', float(np.abs(a - b).max()),
          'scale', float(np.abs(b).max()))
    print(name, 'ratio sample', (a.reshape(2, -1)[:, :3] /
                                 np.where(b.reshape(2, -1)[:, :3] == 0, 1,
                                          b.reshape(2, -1)[:, :3])))
