"""Per-phase K-FAC step time breakdown via the exclude-parts subtraction method.

Capability parity with the reference's breakdown analysis
(reference: scripts/time_breakdown.py:1-83 — stacked phase times for SGD vs
K-FAC; fed by --exclude-parts ablation runs, kfac_preconditioner_base.py:96-99).

On TPU the step is one fused XLA program, so phases cannot be wall-clocked
inside it; this script measures them the way the reference's method does —
by differencing ablated variants (each `exclude_parts` setting compiles a
program *without* that phase):

  FactorComp   = t(full) - t(exclude ComputeFactor... everything downstream)
  InverseComp  = ...

Run it directly; it builds the CIFAR ResNet flagship config and prints the
stacked breakdown. Use --model/--batch for other shapes.

Usage: python scripts/time_breakdown.py [--model resnet32] [--batch 128]
       [--variant eigen_dp] [--num-devices 1]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import build_vision_model, force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import training

# Cumulative ablations, innermost phase first: each setting removes one
# more pipeline stage (reference exclude_parts grammar,
# kfac_preconditioner_base.py:96-99).
LADDER = [
    ('full', ''),
    ('-CommunicateInverse', 'CommunicateInverse'),
    ('-ComputeInverse', 'CommunicateInverse,ComputeInverse'),
    ('-CommunicateFactor',
     'CommunicateInverse,ComputeInverse,CommunicateFactor'),
    ('-ComputeFactor',
     'CommunicateInverse,ComputeInverse,CommunicateFactor,ComputeFactor'),
]


def _time_step(step, state, batch, iters, **kw):
    for _ in range(3):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', default='resnet32')
    ap.add_argument('--batch', type=int, default=128)
    ap.add_argument('--variant', default='eigen_dp')
    ap.add_argument('--num-devices', type=int, default=1)
    ap.add_argument('--iters', type=int, default=10)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    model, img, ncls = build_vision_model(args.model)
    batch = {'input': jnp.asarray(rng.randn(args.batch, img, img, 3),
                                  jnp.float32),
             'label': jnp.asarray(rng.randint(0, ncls, args.batch))}
    tx = training.sgd(0.1, momentum=0.9, weight_decay=5e-4)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    times = {}
    for label, excl in LADDER:
        precond = kfac.KFAC(variant=args.variant, lr=0.1, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=args.num_devices, axis_name=None,
                            exclude_parts=excl)
        state = training.init_train_state(model, tx, precond,
                                          jax.random.PRNGKey(0),
                                          batch['input'])
        step = training.build_train_step(model, tx, precond, ce,
                                         extra_mutable=('batch_stats',))
        times[label] = _time_step(step, state, batch, args.iters,
                                  lr=0.1, damping=0.003)

    # SGD reference (no preconditioner at all)
    state = training.init_train_state(model, tx, None, jax.random.PRNGKey(0),
                                      batch['input'])
    sgd = training.build_train_step(model, tx, None, ce,
                                    extra_mutable=('batch_stats',))
    times['sgd'] = _time_step(sgd, state, batch, args.iters)

    ladder = [times[label] for label, _ in LADDER]
    phases = {
        'FF&BP+update (sgd)': times['sgd'],
        'capture+glue': max(ladder[4] - times['sgd'], 0.0),
        'ComputeFactor': max(ladder[3] - ladder[4], 0.0),
        'CommunicateFactor': max(ladder[2] - ladder[3], 0.0),
        'ComputeInverse': max(ladder[1] - ladder[2], 0.0),
        'CommunicateInverse': max(ladder[0] - ladder[1], 0.0),
    }
    total = times['full']
    print(f'\n{args.model} bs{args.batch} {args.variant} '
          f'nd{args.num_devices} — iter {total * 1e3:.2f} ms '
          f'(SGD {times["sgd"] * 1e3:.2f} ms, '
          f'overhead {total / times["sgd"]:.2f}x)')
    for name, t in phases.items():
        bar = '#' * int(60 * t / total)
        print(f'  {name:<20} {t * 1e3:>8.2f} ms  {bar}')


if __name__ == '__main__':
    main()
