"""Per-phase K-FAC step time breakdown via the exclude-parts subtraction method.

Capability parity with the reference's breakdown analysis
(reference: scripts/time_breakdown.py:1-83 — stacked phase times for SGD vs
K-FAC; fed by --exclude-parts ablation runs, kfac_preconditioner_base.py:96-99).

On TPU the step is one fused XLA program, so phases cannot be wall-clocked
inside it; this script measures them the way the reference's method does —
by differencing ablated variants (each `exclude_parts` setting compiles a
program *without* that phase):

  FactorComp   = t(full) - t(exclude ComputeFactor... everything downstream)
  InverseComp  = ...

Run it directly; it builds the CIFAR ResNet flagship config and prints the
stacked breakdown. Use --model/--batch for other shapes.

Usage: python scripts/time_breakdown.py [--model resnet32] [--batch 128]
       [--variant eigen_dp] [--num-devices 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import build_vision_model, force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import training
from kfac_pytorch_tpu.utils import profiling


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', default='resnet32')
    ap.add_argument('--batch', type=int, default=128)
    ap.add_argument('--variant', default='eigen_dp')
    ap.add_argument('--num-devices', type=int, default=1)
    ap.add_argument('--iters', type=int, default=10)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    model, img, ncls = build_vision_model(args.model)
    batch = {'input': jnp.asarray(rng.randn(args.batch, img, img, 3),
                                  jnp.float32),
             'label': jnp.asarray(rng.randint(0, ncls, args.batch))}
    tx = training.sgd(0.1, momentum=0.9, weight_decay=5e-4)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    def make_step(exclude_parts):
        precond = kfac.KFAC(variant=args.variant, lr=0.1, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=args.num_devices, axis_name=None,
                            exclude_parts=exclude_parts)
        state = training.init_train_state(model, tx, precond,
                                          jax.random.PRNGKey(0),
                                          batch['input'])
        step = training.build_train_step(model, tx, precond, ce,
                                         extra_mutable=('batch_stats',))
        return step, state

    breakdown = profiling.exclude_parts_breakdown(
        make_step, batch, iters=args.iters, lr=0.1, damping=0.003)

    # SGD reference (no preconditioner at all)
    state = training.init_train_state(model, tx, None, jax.random.PRNGKey(0),
                                      batch['input'])
    sgd = training.build_train_step(model, tx, None, ce,
                                    extra_mutable=('batch_stats',))
    sgd_t, _, _ = profiling.time_steps(sgd, state, batch, iters=args.iters,
                                       warmup=3)

    total = breakdown['Total']
    print(f'\n{args.model} bs{args.batch} {args.variant} '
          f'nd{args.num_devices} — iter {total * 1e3:.2f} ms '
          f'(SGD {sgd_t * 1e3:.2f} ms, overhead {total / sgd_t:.2f}x)')
    order = ['ComputeFactor', 'CommunicateFactor', 'ComputeInverse',
             'CommunicateInverse']
    rows = ([('FF&BP+update (sgd)', sgd_t),
             ('capture+glue', max(breakdown['Rest'] - sgd_t, 0.0))]
            + [(p, breakdown[p]) for p in reversed(order)])
    for name, t in rows:
        bar = '#' * int(60 * t / total)
        print(f'  {name:<20} {t * 1e3:>8.2f} ms  {bar}')


if __name__ == '__main__':
    main()
