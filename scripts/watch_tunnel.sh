#!/bin/bash
# No-deadline tunnel watcher (VERDICT r2 next-round #1: "make it
# impossible to miss a tunnel window").
#
# Round-2's watcher had a start deadline and refused to fire in a later
# window ("past start deadline - not launching queue2"). This one has NO
# deadline: it probes forever, and every time the tunnel answers it runs
# the RESUMABLE queue (scripts/run_onchip_queue3.sh) — whose legs are
# guarded by done-markers, so successive windows accumulate progress
# instead of restarting. It exits only when every leg is done.
#
# jax.devices() HANGS (no error) when the tunnel is down, so the probe is
# timeout-wrapped and runs in a throwaway process.
#
# Usage: nohup bash scripts/watch_tunnel.sh >/dev/null 2>&1 &

set -u
cd "$(dirname "$0")/.."
# same state-dir/probe overrides as the queue, so a redirected or
# stubbed rehearsal exercises the watcher too (defaulting here keeps
# watcher and queue pointed at the SAME dir when only one is launched)
D=${QUEUE_STATE_DIR:-logs/onchip}
mkdir -p "$D/done"
W="$D/watch_tunnel.log"
PROBE_EVERY=${WATCH_PROBE_EVERY:-150}   # seconds between probes

echo "[watch] start $(date) pid=$$ probe_every=${PROBE_EVERY}s" >> "$W"

while true; do
  if [ -f "$D/done/ALL" ]; then
    echo "[watch] queue fully complete — exiting $(date)" >> "$W"
    exit 0
  fi
  if bash -c "${QUEUE_PROBE_CMD:-timeout 120 python -c 'import jax; print(jax.devices())'}" \
      >> "$W" 2>/dev/null; then
    echo "[watch] tunnel UP $(date) — running queue3" >> "$W"
    bash scripts/run_onchip_queue3.sh >> "$W" 2>&1
    echo "[watch] queue3 pass ended rc=$? $(date)" >> "$W"
  else
    echo "[watch] probe no-answer $(date +%H:%M:%S)" >> "$W"
  fi
  sleep "$PROBE_EVERY"
done
