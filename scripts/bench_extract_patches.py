"""Per-conv-layer im2col (patch extraction) timing on real model shapes.

Capability parity with the reference's im2col bench
(reference: scripts/bench_extract_patches.py:1-48 — times
`_extract_patches` per conv layer on shapes replayed from logs). Here the
shapes come straight from the model zoo: we init a model, run the capture
pass once to get every conv layer's activation shape, then time
`ops.extract_patches` (which lowers to `lax.conv_general_dilated_patches`,
a single XLA op on the MXU — reference's unfold is a host-visible
gather/reshape chain, kfac/utils.py:33-54).

Usage: python scripts/bench_extract_patches.py [--model resnet32] [--batch 32]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import build_vision_model, force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp

from kfac_pytorch_tpu import capture, ops


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--model', default='resnet32')
    p.add_argument('--batch', type=int, default=32)
    p.add_argument('--img', type=int, default=None)
    args = p.parse_args()

    model, img, _ = build_vision_model(args.model, img=args.img)
    x = jnp.ones((args.batch, img, img, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x, train=False)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    _, acts, _ = capture.apply_with_capture(model, variables, x, train=False)

    total = 0.0
    print(f'{"layer":<44} {"act shape":<24} {"patch (ms)":>11}')
    for meta in metas.values():
        if meta.kind != 'conv':
            continue
        a = capture.layer_act(acts, meta)
        fn = jax.jit(lambda t, m=meta: ops.extract_patches(
            t, m.kernel_size, m.strides, m.padding))
        # vary inputs per iteration (remote execution caches can
        # serve identical repeats — scripts/utils.timeit)
        t = timeit(fn, a, vary=lambda i, a=a: (a + 1e-3 * i,))
        total += t
        print(f'{meta.name:<44} {str(tuple(a.shape)):<24} {t * 1e3:>11.3f}')
    print(f'total per-step patch-extraction time: {total * 1e3:.3f} ms')


if __name__ == '__main__':
    main()
