#!/bin/bash
# Round-3 on-chip queue — RESUMABLE. Every leg is guarded by a
# done-marker ("$D"/done/<tag>.done, created on rc=0), so the
# watcher (scripts/watch_tunnel.sh) can re-run this script in every
# tunnel window and only the unfinished legs execute. Before each leg the
# tunnel is re-probed; if it stopped answering, the pass aborts and the
# watcher retries in the next window.
#
# ORDERED BY ROUND VALUE (VERDICT r2 #1/#2/#6/#9/#7): the official fenced
# headline first — it also primes the compile cache for the driver's
# end-of-round bench.py run — then the phase breakdown, the warm-eigen
# decision legs, the op/attention A/Bs, then on-chip convergence.
#
# All measurements use the fixed fence (utils/profiling.host_fence):
# jax.block_until_ready does NOT fence on this platform.
#
# Usage: bash scripts/run_onchip_queue3.sh   (the watcher does this)

set -u
cd "$(dirname "$0")/.."
# QUEUE_STATE_DIR redirects every marker/log/harvest path — the CPU
# rehearsal (tests and pre-window dry runs) must never touch the real
# on-chip markers. QUEUE_PROBE_CMD stubs the tunnel probe the same way.
if [ -n "${QUEUE_SMOKE:-}" ]; then
  # a rehearsal must NEVER touch the real on-chip markers: smoke mode
  # defaults its own state dir (and self-contains the bench smoke
  # sizes below) unless one was given explicitly
  D=${QUEUE_STATE_DIR:-logs/queue_smoke}
else
  D=${QUEUE_STATE_DIR:-logs/onchip}
fi
mkdir -p "$D/done"
TS=$(date +%m%d_%H%M)
L="$D/queue3_${TS}"
S="$L.summary"

probe() {
  bash -c "${QUEUE_PROBE_CMD:-timeout 120 python -c 'import jax; print(jax.devices())'}" \
    > /dev/null 2>&1
}

MAX_ATTEMPTS=${QUEUE_MAX_ATTEMPTS:-3}

# QUEUE_SMOKE=1: shrink every leg's workload so the ENTIRE queue can be
# rehearsed end-to-end on the CPU mesh before it ever burns a tunnel
# window (export KFAC_PLATFORM=cpu and the BENCH_* smoke sizes too —
# bench.py reads those from the environment). The real path is the
# unset case: identical commands with full-size arguments.
if [ -n "${QUEUE_SMOKE:-}" ]; then
  FLASH_LENS="64 128"; FLASH_BIG=256; OPS_ARGS="--dims 64 128"
  PAIRED_DIMS="64 128"; EPOCHS=2
  # self-contained CPU rehearsal: the bench.py legs read these from the
  # environment — without them a "rehearsal" would run full-size
  # resnet50 benching for hours
  export KFAC_PLATFORM=${KFAC_PLATFORM:-cpu}
  export KFAC_HOST_DEVICES=${KFAC_HOST_DEVICES:-1}
  export BENCH_MODEL=${BENCH_MODEL:-resnet20} BENCH_IMG=${BENCH_IMG:-32}
  export BENCH_BATCH=${BENCH_BATCH:-8} BENCH_ITERS=${BENCH_ITERS:-3}
else
  FLASH_LENS="8192 16384"; FLASH_BIG=32768; OPS_ARGS=""
  PAIRED_DIMS="512 1024"; EPOCHS=100
fi

# bench.py legs set NEXT_NO_DONE=1: rc=0 alone must NOT mark them done
# (bench.py exits 0 even when its defining optional leg was budget-
# skipped) — for those legs harvest() is the only done-setter, keyed on
# the measurement actually landing in the JSON.
NEXT_NO_DONE=0

run() {  # run <tag> <timeout_s> <cmd...>
  local tag=$1 to=$2; shift 2
  local no_done=$NEXT_NO_DONE; NEXT_NO_DONE=0
  if [ -f "$D/done/$tag.done" ]; then
    echo "[skip] $tag (done)" | tee -a "$S"; return 0
  fi
  # a leg that fails MAX_ATTEMPTS times with the tunnel up is a real
  # failure (e.g. the 32k XLA compile): record it and stop burning
  # tunnel windows on it — .gaveup counts as terminal for ALL below
  local att_f="$D/done/$tag.attempts"
  local att; att=$(cat "$att_f" 2>/dev/null || echo 0)
  if [ "$att" -ge "$MAX_ATTEMPTS" ]; then
    touch "$D/done/$tag.gaveup"
    echo "[gaveup] $tag after $att attempts" | tee -a "$S"; return 1
  fi
  if ! probe; then
    echo "[abort] tunnel went away before $tag $(date +%H:%M:%S)" \
      | tee -a "$S"
    exit 1
  fi
  echo "=== [$tag] attempt $((att + 1)) $(date +%H:%M:%S) " \
       "timeout=${to}s: $*" | tee -a "$S"
  # -k: if the leg ignores TERM (wedged backend thread), KILL it 60s
  # later so the queue never hangs behind one stuck process
  timeout -k 60 "$to" "$@" > "$L.$tag.log" 2>&1
  local rc=$?
  echo "=== [$tag] rc=$rc $(date +%H:%M:%S)" | tee -a "$S"
  tail -5 "$L.$tag.log" >> "$S"
  if [ "$rc" -eq 0 ] && [ "$no_done" -eq 0 ]; then
    touch "$D/done/$tag.done"
  elif [ "$rc" -ne 0 ] && probe; then
    # tunnel still up => the failure was the leg's own, count it;
    # tunnel down => environmental, don't charge the leg
    echo $((att + 1)) > "$att_f"
  fi
  return $rc
}

harvest() {  # harvest <tag> <required_key> <rc> — after a bench.py leg,
  # regardless of rc: bench.py emits (partial) JSON even when TERMed and
  # checkpoints it to a file even when SIGKILLed mid-C-call, so recover
  # the result from the log (preferred) or the checkpoint file, and if
  # the leg's DEFINING measurement (required_key: "value" or an
  # extra.<key>) is non-null, count the leg done. When rc=0 but the key
  # is missing (budget-skipped), charge an attempt so the leg can't
  # rc=0-loop forever.
  local tag=$1 key=$2 rc=$3
  local line
  line=$(grep -h '"metric"' "$L.$tag.log" 2>/dev/null | tail -1)
  if [ -z "$line" ] && [ -f "$D/$tag.partial.json" ]; then
    line=$(cat "$D/$tag.partial.json")
  fi
  [ -n "$line" ] || return 0
  printf '%s\n' "$line" > "$D/$tag.json"
  if [ -f "$D/done/$tag.done" ]; then return 0; fi
  if printf '%s' "$line" | KEY="$key" python -c '
import json, os, sys
d = json.load(sys.stdin)
k = os.environ["KEY"]
v = d.get(k) if k == "value" else d.get("extra", {}).get(k)
sys.exit(0 if v is not None else 1)' 2>/dev/null; then
    echo "[harvest] $tag: JSON carries $key — marking done" | tee -a "$S"
    touch "$D/done/$tag.done"
  elif [ "$rc" -eq 0 ]; then
    local att_f="$D/done/$tag.attempts"
    local att; att=$(cat "$att_f" 2>/dev/null || echo 0)
    echo $((att + 1)) > "$att_f"
    echo "[harvest] $tag: rc=0 but $key missing — attempt charged" \
      | tee -a "$S"
  fi
}

# 1. THE official-number candidate: fenced headline bench (inverse_dp
#    freq-1 measured FIRST inside bench.py; partial JSON on timeout).
#    Keep the JSON where the round summary can cite it.
NEXT_NO_DONE=1
run bench_headline 5400 env \
    BENCH_PARTIAL_PATH="$D"/bench_headline.partial.json \
    python bench.py
harvest bench_headline value $?

# 2. fenced per-phase breakdown (VERDICT #6): the table to set against
#    the reference's FactorComp/FactorComm/InverseComp/InverseComm ledger.
#    Budget raised so the earlier optional legs can't starve the
#    breakdown ladder out of its own run.
NEXT_NO_DONE=1
run bench_breakdown 7200 env BENCH_BREAKDOWN=1 BENCH_TIME_BUDGET=5000 \
    BENCH_PARTIAL_PATH="$D"/bench_breakdown.partial.json \
    python bench.py
harvest bench_breakdown phase_breakdown_s $?

# 3. warm-eigen decision legs (VERDICT #2): eigen_dp stock freq-10 /
#    basis-amortized / warm-subspace — is the reference default rescued?
#    Required key = the LAST eigen leg, so a partial run can't mark the
#    decision data done before all three legs exist.
NEXT_NO_DONE=1
run bench_full 7200 env BENCH_FULL=1 BENCH_TIME_BUDGET=5000 \
    BENCH_PARTIAL_PATH="$D"/bench_full.partial.json \
    python bench.py
harvest bench_full ekfac_iter_s_freq10_basis100 $?

# 4. fenced op micro legs (the retired scripts/bench_ops.py +
#    bench_extract_patches.py folded into the BENCH_MICRO emission
#    contract, ISSUE 19): decomp_impl ladder steady state + the
#    capture-kernel head-to-head (fused Pallas vs unfused XLA, with
#    the standalone patch-extract cost alongside) — one JSON line,
#    partial-emission resumable like every other leg
run bench_ops 5400 env BENCH_MICRO=1 \
    BENCH_PARTIAL_PATH="$D"/bench_micro_ops.partial.json \
    python bench.py

# 5. paired-rotation jacobi keep/drop decision (VERDICT #9), under the
#    same micro contract (KFAC_JACOBI_ROT reaches ops.jacobi_eigh
#    through the env at trace time)
run bench_ops_paired 3600 env KFAC_JACOBI_ROT=paired BENCH_MICRO=1 \
    BENCH_PARTIAL_PATH="$D"/bench_micro_paired.partial.json \
    python bench.py

# 6. flash forward crossover re-check under the fixed fence + the 32k
#    XLA retry (VERDICT #3/#7): both columns at 8k/16k/32k
run flash_fwd_xover 3600 python scripts/bench_flash.py \
    --seq-lens $FLASH_LENS --impls xla pallas
run flash_32k_xla 1800 python scripts/bench_flash.py --seq-lens $FLASH_BIG \
    --impls xla
run flash_32k_pallas 1800 python scripts/bench_flash.py --seq-lens $FLASH_BIG \
    --impls pallas

# 6b. forward tile sweep (VERDICT r2 weak #3 alternative): can larger
#     K/Q tiles close the Pallas-vs-XLA gap at 8k/16k? Trace-time env
#     knobs, one process per config.
run flash_tile_tk512 2700 env KFAC_FLASH_TK=512 \
    python scripts/bench_flash.py --seq-lens $FLASH_LENS --impls pallas
# 1024 is the VMEM clamp ceiling (ops/pallas_attention._fwd_tile):
# requesting 2048 would silently re-measure the 1024 point. A prior
# pass's tk2048 marker covers the IDENTICAL clamped config — migrate
# it instead of burning a tunnel window re-measuring the same point.
for ext in done gaveup attempts; do
  if [ -f "$D/done/flash_tile_tk2048.$ext" ] \
     && [ ! -f "$D/done/flash_tile_tk1024.$ext" ]; then
    mv "$D/done/flash_tile_tk2048.$ext" "$D/done/flash_tile_tk1024.$ext"
  fi
done
run flash_tile_tk1024 2700 env KFAC_FLASH_TK=1024 \
    python scripts/bench_flash.py --seq-lens $FLASH_LENS --impls pallas
run flash_tile_tq512_tk512 2700 env KFAC_FLASH_TQ=512 KFAC_FLASH_TK=512 \
    python scripts/bench_flash.py --seq-lens $FLASH_LENS --impls pallas

# 7. on-chip real-data convergence: digits-CIFAR (hardened task),
#    unmodified reference recipe; K-FAC vs SGD vs warm-subspace.
#    The training legs run only once mkdata has SUCCEEDED — without the
#    dataset they would burn their attempts (and hours of tunnel time)
#    failing on the root cause mkdata still has retries left for.
run mkdata 300 python scripts/make_digits_cifar.py
if [ -f "$D/done/mkdata.done" ]; then
  run digits_kfac 7200 env data_dir=/tmp/digits_cifar nworkers=1 kfac=1 \
      epochs=$EPOCHS bash train_cifar10.sh
  run digits_sgd 7200 env data_dir=/tmp/digits_cifar nworkers=1 kfac=0 \
      epochs=$EPOCHS bash train_cifar10.sh
  run digits_kfac_subspace 7200 env data_dir=/tmp/digits_cifar nworkers=1 \
      kfac=1 epochs=$EPOCHS KFAC_EIGH_IMPL=subspace bash train_cifar10.sh \
      --kfac-warm-start
else
  echo "[defer] digits legs await mkdata" | tee -a "$S"
fi

# all legs terminal (done or given up)? tell the watcher to stand down
all_done=1
for tag in bench_headline bench_breakdown bench_full bench_ops \
           bench_ops_paired flash_fwd_xover flash_32k_xla \
           flash_32k_pallas flash_tile_tk512 flash_tile_tk1024 \
           flash_tile_tq512_tk512 mkdata digits_kfac digits_sgd \
           digits_kfac_subspace; do
  [ -f "$D/done/$tag.done" ] || \
    [ -f "$D/done/$tag.gaveup" ] || all_done=0
done
if [ "$all_done" -eq 1 ]; then
  touch "$D"/done/ALL
  echo "QUEUE3 COMPLETE $(date)" | tee -a "$S"
fi
