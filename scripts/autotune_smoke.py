"""Closed-loop autotune smoke: the CI gate for the online KnobController.

Five legs, each writing its decision log as a JSONL artifact:

1. **synthetic** (jax-free, fully deterministic — no wall clock): a
   planted cost profile whose refresh spike amortizes with frequency
   (optimum = the ladder top) drives the controller through
   ``record``. Gate: the final ``kfac_update_freq`` matches the
   planted optimum, steady state is reached within a bounded number of
   probe windows, and the run had ZERO drift vetoes (nothing to veto —
   a veto here would mean the gate fires spuriously).
2. **drift-hold** (jax-free): the same improving feed on the MODELED
   chip with measured phase marginals far outside the perf model's
   [optimistic, conservative] band. Gate: zero knob changes committed
   (the acceptance criterion — the tuner never commits a change whose
   measured phase ratio leaves the band), every improving candidate
   vetoed.
3. **decomp-ladder** (jax-free): the inverse-free rung
   (``decomp_impl``) under a planted optimum — the newton_schulz rung
   is genuinely cheaper, the controller must converge onto it with
   ZERO vetoes of any kind.
4. **quality-hold** (jax-free): the numerical-health gate — the
   iterative rung is FASTER but raises the badness counter
   (``quality_gate``) during its probe window. Gate: zero commits
   (an accuracy-regressing rung never lands on speed alone), at least
   one quality veto, steady at the cold kernel.
5. **measured** (``AUTOTUNE_SMOKE_MEASURED=1``, needs a jax CPU
   backend): ``bench._micro_autotune()`` — the controller starts the
   real micro-MLP trainer at the pessimal cadence (kfac_update_freq=1)
   and must climb to the best hand-configured cadence of the same
   sweep, with steady-state step time within ``AUTOTUNE_SMOKE_TOL``
   (default 1.10x) of the hand-tuned best.

Usage:
  KFAC_PLATFORM=cpu KFAC_AUTOTUNE_ASSERT=1 AUTOTUNE_SMOKE_MEASURED=1 \
      python scripts/autotune_smoke.py

Env knobs:
  KFAC_AUTOTUNE_ASSERT    '1' = violations exit nonzero (the CI gate);
                          unset = report-only (summary still written)
  AUTOTUNE_SMOKE_MEASURED '1' = run the measured micro-bench leg
  AUTOTUNE_SMOKE_DIR      artifact dir (default '.'): per-leg
                          autotune-decisions-<leg>.jsonl + summary
                          autotune-smoke.json
  AUTOTUNE_SMOKE_TOL      measured-leg steady/hand-best ratio ceiling
                          (default 1.10 — CPU wall times are noisy;
                          the convergence check is the sharp pin)
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from kfac_pytorch_tpu import autotune


class _FakePrecond:
    """Knob attributes only — the synthetic legs never touch jax."""

    def __init__(self, fac=1, kfac=1):
        self.fac_update_freq = fac
        self.kfac_update_freq = kfac
        self.damping = 0.003
        self.comm_precision = None
        self.axis_name = None


def _feed(ctl, pre, model, steps):
    fed = 0
    while fed < steps and ctl.state != 'steady':
        F = pre.kfac_update_freq
        for i in range(F):
            phases, cost = model(F, i)
            ctl.record(phases, cost)
            fed += 1
            if fed >= steps:
                break
    return fed


def leg_synthetic(art_dir):
    """Planted optimum at the ladder top: refresh cost 0.5 amortizes,
    steady steps cost 0.01 — every doubling wins until the cap."""
    optimum = 8
    pre = _FakePrecond(kfac=1)
    ctl = autotune.KnobController(
        pre, window=16, settle=1, rel_improve=0.03, dwell_windows=1,
        cooldown=2, steady_every=0, tune=('kfac_update_freq',),
        freq_bounds=(1, optimum),
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-synthetic.jsonl'))

    def model(F, i):
        if i == 0:
            return ('pred', 'stats', 'decomp', 'gather'), 0.51
        return ('pred',), 0.01

    steps = _feed(ctl, pre, model, 2000)
    failures = []
    if pre.kfac_update_freq != optimum:
        failures.append(f'final kfac_update_freq={pre.kfac_update_freq} '
                        f'!= planted optimum {optimum}')
    if ctl.state != 'steady':
        failures.append(f'no steady state after {steps} steps')
    if ctl.windows > 30:
        failures.append(f'{ctl.windows} probe windows (bound: 30)')
    if ctl.vetoes:
        failures.append(f'{ctl.vetoes} spurious drift vetoes')
    return {'leg': 'synthetic', 'planted_optimum': optimum,
            'final_kfac_update_freq': pre.kfac_update_freq,
            'steps': steps, 'windows': ctl.windows,
            'commits': ctl.commits, 'reverts': ctl.reverts,
            'vetoes': ctl.vetoes, 'failures': failures}


def leg_drift_hold(art_dir):
    """The veto acceptance criterion: on the modeled chip an improving
    candidate whose measured phase ratios leave the band NEVER
    commits."""
    from kfac_pytorch_tpu import perfmodel
    pre = _FakePrecond(kfac=4)
    ctl = autotune.KnobController(
        pre, window=4, settle=0, rel_improve=0.03, dwell_windows=1,
        cooldown=50, steady_every=0, tune=('kfac_update_freq',),
        freq_bounds=(1, 8), predicted=perfmodel.predict_block(),
        platform='TPU v5e', variant='eigen_dp',
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-drift.jsonl'))
    ctl._seeded = 'done'  # isolate the gate from prior seeding
    # baseline 0.6 s, every probe 'improves' to 0.5 s — but a 0.5 s
    # pred-only step is orders outside the modeled per-phase band:
    # both neighbors get vetoed onto cooldown and the controller must
    # settle STEADY at the original knob
    for w in range(12):
        cost = 0.6 if ctl.state == 'baseline' else 0.5
        for _ in range(4):
            ctl.record(('pred',), cost)
    failures = []
    if ctl.state != 'steady':
        failures.append(f'no steady state after the vetoes '
                        f'(state={ctl.state})')
    if ctl.commits:
        failures.append(f'{ctl.commits} commits landed on the modeled '
                        'chip with out-of-band phase ratios')
    if not ctl.vetoes:
        failures.append('no drift veto fired on an out-of-band '
                        'improving candidate')
    if pre.kfac_update_freq != 4:
        failures.append(f'knob moved to {pre.kfac_update_freq} despite '
                        'the veto')
    return {'leg': 'drift_hold', 'platform': 'TPU v5e',
            'commits': ctl.commits, 'vetoes': ctl.vetoes,
            'final_kfac_update_freq': pre.kfac_update_freq,
            'failures': failures}


class _FakeDecompPrecond(_FakePrecond):
    def __init__(self, method='cholesky', decomp_impl='xla', **kw):
        super().__init__(**kw)
        self.method = method
        self.decomp_impl = decomp_impl


def leg_decomp_ladder(art_dir):
    """Planted optimum on the inverse-free rung: newton_schulz's
    decomposition marginal is 4x cheaper — the controller must land on
    it with zero spurious vetoes."""
    pre = _FakeDecompPrecond(kfac=4)
    ctl = autotune.KnobController(
        pre, window=8, settle=1, rel_improve=0.03, dwell_windows=1,
        cooldown=2, steady_every=0, tune=('decomp_impl',),
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-decomp.jsonl'))

    def model(F, i):
        decomp = 0.4 if pre.decomp_impl == 'xla' else 0.1
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + decomp
        return ('pred',), 0.01

    steps = _feed(ctl, pre, model, 1000)
    failures = []
    if pre.decomp_impl != 'newton_schulz':
        failures.append(f'final decomp_impl={pre.decomp_impl} != planted '
                        'optimum newton_schulz')
    if ctl.state != 'steady':
        failures.append(f'no steady state after {steps} steps')
    if ctl.vetoes:
        failures.append(f'{ctl.vetoes} spurious vetoes')
    return {'leg': 'decomp_ladder', 'planted_optimum': 'newton_schulz',
            'final_decomp_impl': pre.decomp_impl, 'steps': steps,
            'commits': ctl.commits, 'vetoes': ctl.vetoes,
            'failures': failures}


def leg_quality_hold(art_dir):
    """The numerical-health acceptance criterion: a FASTER iterative
    rung whose probe window raises the badness counter never commits."""
    pre = _FakeDecompPrecond(kfac=4)
    events = {'n': 0}
    ctl = autotune.KnobController(
        pre, window=8, settle=1, rel_improve=0.03, dwell_windows=1,
        cooldown=50, steady_every=0, tune=('decomp_impl',),
        quality_gate=lambda: events['n'],
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-quality.jsonl'))

    def model(F, i):
        if pre.decomp_impl == 'newton_schulz':
            events['n'] += 1                  # accuracy regressing...
            decomp = 0.05                     # ...but much faster
        else:
            decomp = 0.4
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + decomp
        return ('pred',), 0.01

    steps = _feed(ctl, pre, model, 1000)
    failures = []
    if ctl.commits:
        failures.append(f'{ctl.commits} commits of an accuracy-'
                        'regressing rung')
    if not ctl.quality_vetoes:
        failures.append('no quality veto fired')
    if pre.decomp_impl != 'xla':
        failures.append(f'knob moved to {pre.decomp_impl} despite the '
                        'quality veto')
    if ctl.state != 'steady':
        failures.append(f'no steady state after {steps} steps '
                        f'(state={ctl.state})')
    return {'leg': 'quality_hold', 'commits': ctl.commits,
            'quality_vetoes': ctl.quality_vetoes,
            'final_decomp_impl': pre.decomp_impl, 'steps': steps,
            'failures': failures}


class _FakeCapturePrecond(_FakePrecond):
    def __init__(self, capture_impl='xla', **kw):
        super().__init__(**kw)
        self.capture_impl = capture_impl


def leg_capture_ladder(art_dir):
    """Planted optimum on the fused-capture rung (ISSUE 19): the pallas
    kernels' per-window capture marginal is 4x cheaper — the controller
    must land on the fused rung with zero spurious vetoes."""
    pre = _FakeCapturePrecond(kfac=4)
    ctl = autotune.KnobController(
        pre, window=8, settle=1, rel_improve=0.03, dwell_windows=1,
        cooldown=2, steady_every=0, tune=('capture_impl',),
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-capture.jsonl'))

    def model(F, i):
        stats = 0.4 if pre.capture_impl == 'xla' else 0.1
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + stats
        return ('pred',), 0.01

    steps = _feed(ctl, pre, model, 1000)
    failures = []
    if pre.capture_impl != 'pallas':
        failures.append(f'final capture_impl={pre.capture_impl} != '
                        'planted optimum pallas')
    if ctl.state != 'steady':
        failures.append(f'no steady state after {steps} steps')
    if ctl.vetoes:
        failures.append(f'{ctl.vetoes} spurious vetoes')
    return {'leg': 'capture_ladder', 'planted_optimum': 'pallas',
            'final_capture_impl': pre.capture_impl, 'steps': steps,
            'commits': ctl.commits, 'vetoes': ctl.vetoes,
            'failures': failures}


class _FakeCommModePrecond(_FakePrecond):
    """comm-mode-switchable fake (ISSUE 14): a planted analytic byte
    model (pred ships 64 MiB every step, inverse 8 MiB per refresh) and
    a replan stub that records the applied switch — everything the
    controller's comm_mode rung needs, no jax anywhere."""

    def __init__(self, mode='pred', **kw):
        super().__init__(**kw)
        self.comm_mode = mode
        self.axis_name = 'batch'
        self.method = 'eigh'
        self.ekfac = False
        self.comm_prefetch = False
        self.replans = []
        outer = self

        class _Plan:
            def comm_volume(self, *, stats_reduce, method,
                            comm_precision='fp32', comm_mode=None,
                            decomp_shard=None):
                mode = comm_mode or outer.comm_mode
                return {'FactorComm': 0,
                        'InverseComm': (8 << 20) if mode == 'inverse'
                        else 0,
                        'PredComm': (64 << 20) if mode == 'pred' else 0,
                        'DecompComm': 0}

        self.plan = _Plan()

    def request_replan(self, _invalidate=True, **spec):
        self.replans.append(dict(spec))


def leg_comm_mode(art_dir):
    """The applied comm-mode switch (ISSUE 14 acceptance): a planted
    comm-bound profile where comm_pred costs 0.05 s every step and
    comm_inverse amortizes to ~0.015 s — the analytic verdict seeds the
    inverse candidate first, the measured probe wins, the controller
    COMMITS (decision log shows an *applied*, not advisory, commit via
    KFAC.replan) and steady state beats the starting mode."""
    pre = _FakeCommModePrecond(mode='pred', kfac=4)
    ctl = autotune.KnobController(
        pre, window=8, settle=1, rel_improve=0.03, dwell_windows=1,
        cooldown=2, steady_every=0, tune=('comm_mode',),
        decision_log=os.path.join(art_dir,
                                  'autotune-decisions-comm-mode.jsonl'))

    def model(F, i):
        if pre.comm_mode == 'pred':
            # the pred gather ships every step: comm-bound flat profile
            return ('pred',), 0.05
        if i == 0:
            return ('pred', 'stats', 'decomp', 'gather'), 0.03
        return ('pred',), 0.01

    steps = _feed(ctl, pre, model, 1000)
    failures = []
    if pre.comm_mode != 'inverse':
        failures.append(f'final comm_mode={pre.comm_mode} — the planted '
                        'comm-bound profile was not applied')
    commits = [d for d in ctl.decisions
               if d['kind'] == 'commit' and d.get('knob') == 'comm_mode']
    if not commits:
        failures.append('no comm_mode commit in the decision log')
    elif not commits[0].get('applied'):
        failures.append('comm_mode commit is not marked applied '
                        '(advisory-only regression)')
    if ctl.comm_mode_choice != 'inverse':
        failures.append(f'analytic prior chose {ctl.comm_mode_choice}, '
                        "expected 'inverse' (seeded-prior regression)")
    if not pre.replans:
        failures.append('no KFAC.request_replan recorded — the commit '
                        'did not route through the live replanning path')
    steady_t = (ctl.last_window or {}).get('time_s')
    if steady_t is None or steady_t >= 0.05:
        failures.append(f'steady-state window {steady_t}s does not beat '
                        'the starting mode (0.05 s/step)')
    if ctl.state != 'steady':
        failures.append(f'no steady state after {steps} steps '
                        f'(state={ctl.state})')
    return {'leg': 'comm_mode', 'final_comm_mode': pre.comm_mode,
            'prior_choice': ctl.comm_mode_choice,
            'replans': list(pre.replans), 'steady_window_s': steady_t,
            'commits': ctl.commits, 'steps': steps, 'failures': failures}


def leg_measured(art_dir, tol):
    """bench._micro_autotune on a real CPU backend: pessimal start,
    hand-configured sweep as the yardstick."""
    import bench
    block = bench._micro_autotune()
    with open(os.path.join(art_dir,
                           'autotune-decisions-measured.jsonl'), 'w') as f:
        for d in block['controller']['decisions_tail']:
            f.write(json.dumps(d) + '\n')
    failures = []
    if not block['converged_to_hand_best']:
        failures.append(
            f"final kfac_update_freq={block['final_kfac_update_freq']} "
            f"!= hand best {block['hand_best']['kfac_update_freq']}")
    if block['steady_over_hand_best'] > tol:
        failures.append(
            f"steady {block['steady_mean_ms']}ms is "
            f"{block['steady_over_hand_best']}x the hand best "
            f"{block['hand_best']['mean_ms']}ms (tol {tol}x)")
    if block['controller']['vetoes']:
        failures.append(f"{block['controller']['vetoes']} drift vetoes "
                        'on an unmodeled platform')
    block['leg'] = 'measured'
    block['failures'] = failures
    return block


def main():
    art_dir = os.environ.get('AUTOTUNE_SMOKE_DIR', '.')
    os.makedirs(art_dir, exist_ok=True)
    tol = float(os.environ.get('AUTOTUNE_SMOKE_TOL', '1.10'))
    legs = [leg_synthetic(art_dir), leg_drift_hold(art_dir),
            leg_decomp_ladder(art_dir), leg_capture_ladder(art_dir),
            leg_quality_hold(art_dir), leg_comm_mode(art_dir)]
    if os.environ.get('AUTOTUNE_SMOKE_MEASURED') == '1':
        legs.append(leg_measured(art_dir, tol))
    failures = [f for leg in legs for f in leg['failures']]
    summary = {'ok': not failures, 'failures': failures, 'legs': legs}
    out = os.path.join(art_dir, 'autotune-smoke.json')
    with open(out, 'w') as f:
        json.dump(summary, f, indent=2)
    for leg in legs:
        status = 'ok' if not leg['failures'] else 'FAIL'
        print(f"autotune-smoke: {leg['leg']}: {status}"
              + (f" {leg['failures']}" if leg['failures'] else ''))
    print(f'autotune-smoke: summary -> {out}')
    if failures and os.environ.get('KFAC_AUTOTUNE_ASSERT') == '1':
        print('autotune-smoke: ASSERT FAILED', file=sys.stderr)
        for f in failures:
            print(f'  - {f}', file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
