#!/bin/bash
# Second on-chip batch (round-2 session 4), rebuilt after the fencing
# discovery: jax.block_until_ready does NOT fence execution on the tunnel
# platform (scripts/check_eigh_onchip.py), so every measurement here uses
# the fixed harness (host-fetch fence + per-iteration input jitter).
# Sequential, timeout-wrapped, logs under logs/onchip/.
#
# ORDERED BY ROUND VALUE (the tunnel has been down for hours and may not
# stay up): the official bench artifacts first — they also prime the
# compile cache for the driver's end-of-round bench.py run — then the
# decision A/Bs, then convergence legs.
#
# Dropped from the original plan: BENCH_FULL KFAC_EIGH_IMPL=jacobi legs —
# the real-fenced probe shows batched Jacobi loses to XLA QDWH per matrix
# at 512 (>=1.6x) and collapses (~79 s/call) at 1024. The 'paired'
# rotation form gets one cheap bench_ops probe instead.
#
# Usage: nohup bash scripts/run_onchip_queue2.sh &

set -u
cd "$(dirname "$0")/.."
mkdir -p logs/onchip
TS=$(date +%m%d_%H%M)
L="logs/onchip/queue2_${TS}"

run() {  # run <tag> <timeout_s> <cmd...>
  local tag=$1 to=$2; shift 2
  echo "=== [$tag] $(date +%H:%M:%S) timeout=${to}s: $*" | tee -a "$L.summary"
  timeout "$to" "$@" > "$L.$tag.log" 2>&1
  local rc=$?
  echo "=== [$tag] rc=$rc $(date +%H:%M:%S)" | tee -a "$L.summary"
  tail -5 "$L.$tag.log" >> "$L.summary"
  return $rc
}

run probe 120 python -c "import jax; print(jax.devices())" || {
  echo "tunnel down — aborting queue2" | tee -a "$L.summary"; exit 1; }

# 1. headline bench with the real fence — the official-number candidate
#    (includes the warm Newton-Schulz freq-1 measurement)
run bench_headline 5400 python bench.py

# 2. full bench: + eigen_dp stock / basis-amortized / warm-subspace legs
run bench_full 7200 env BENCH_FULL=1 python bench.py

# 3. op micro legs (scripts/bench_ops.py retired into bench.py's
#    BENCH_MICRO mode, ISSUE 19) — decides the eigh precision default
run bench_ops 5400 env BENCH_MICRO=1 python bench.py

# 4. flash A/B re-run under the fixed harness (confirm the auto-bwd
#    crossover measured with the old fence)
run flash_ab 3600 python scripts/bench_flash.py \
    --seq-lens 8192 32768 --bwd-impls pallas recompute

# 5. the gather-free paired-rotation jacobi: keep or delete the knob
run bench_ops_paired 3600 env KFAC_JACOBI_ROT=paired BENCH_MICRO=1 \
    python bench.py

# 6. per-phase breakdown on the flagship config (5 extra programs)
run bench_breakdown 7200 env BENCH_BREAKDOWN=1 python bench.py

# 7. real-data convergence ON CHIP: digits-CIFAR, unmodified reference
#    recipe (ResNet-32, bs128, damping 0.03), K-FAC leg + SGD leg
[ -d /tmp/digits_cifar ] || run mkdata 300 python scripts/make_digits_cifar.py
run digits_kfac 7200 env data_dir=/tmp/digits_cifar nworkers=1 kfac=1 \
    epochs=100 bash train_cifar10.sh
run digits_sgd 7200 env data_dir=/tmp/digits_cifar nworkers=1 kfac=0 \
    epochs=100 bash train_cifar10.sh
#    + the warm-subspace kernel on the same recipe: convergence evidence
#    for ops.subspace_eigh on real data (vs the stock-XLA kfac leg above)
run digits_kfac_subspace 7200 env data_dir=/tmp/digits_cifar nworkers=1 \
    kfac=1 epochs=100 KFAC_EIGH_IMPL=subspace bash train_cifar10.sh \
    --kfac-warm-start

# 8. retry the XLA blockwise attention path at 32k (was an HTTP 500 from
#    the remote-compile service — flaky-or-real check)
run flash_32k_xla 1800 python scripts/bench_flash.py --seq-lens 32768 \
    --impls xla

echo "QUEUE2 COMPLETE $(date)" | tee -a "$L.summary"
