"""Single-chip attention kernel A/B: Pallas flash block vs plain XLA.

Times one fwd+bwd causal attention call at growing sequence length with
both block implementations (`parallel/ring_attention.py` dispatch). The
XLA path materializes the [L, L] score block in HBM; the Pallas kernel
streams K/V tiles through VMEM — the gap grows with L until the XLA path
OOMs, which is the kernel's reason to exist.

Usage: python scripts/bench_flash.py [--seq-lens 1024 4096 16384]
       [--heads 8] [--d-head 64] [--batch 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu.parallel.ring_attention import ring_attention


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--seq-lens', nargs='+', type=int,
                    default=[1024, 4096, 8192, 16384])
    ap.add_argument('--batch', type=int, default=1)
    ap.add_argument('--heads', type=int, default=8)
    ap.add_argument('--d-head', type=int, default=64)
    ap.add_argument('--impls', nargs='+', default=None,
                    help="default: xla + (pallas on tpu | "
                         "pallas_interpret elsewhere)")
    ap.add_argument('--bwd-impls', nargs='+', default=None,
                    choices=['pallas', 'recompute'],
                    help='A/B the pallas-path backward: each entry times '
                         'the pallas block impl with this backward '
                         '(KFAC_ATTN_BWD_IMPL is set before tracing)')
    args = ap.parse_args()

    on_tpu = jax.default_backend() == 'tpu'
    if args.impls and args.bwd_impls:
        raise SystemExit('--impls and --bwd-impls are mutually exclusive '
                         '(bwd mode pins the pallas forward)')
    impls = args.impls or ['xla', 'pallas' if on_tpu else
                           'pallas_interpret']
    tile_env = [k for k in ('KFAC_FLASH_TQ', 'KFAC_FLASH_TK')
                if k in os.environ]
    print(f'device: {jax.devices()[0]}; B={args.batch} H={args.heads} '
          f'D={args.d_head}; fwd+bwd causal attention')
    if tile_env:
        # report the EFFECTIVE tile per length — _fwd_tile clamps/rounds
        # the request (e.g. 480->128), so echoing the raw env would
        # misattribute sweep rows
        from kfac_pytorch_tpu.ops.pallas_attention import _fwd_tile
        for L in args.seq_lens:
            eff = {k: _fwd_tile(k, 128, L) for k in tile_env}
            print(f'  L={L:>7} effective tiles: {eff}')

    for L in args.seq_lens:
        rng = np.random.RandomState(0)
        shape = (args.batch, args.heads, L, args.d_head)
        q = jnp.asarray(rng.randn(*shape), jnp.float32)
        k = jnp.asarray(rng.randn(*shape), jnp.float32)
        v = jnp.asarray(rng.randn(*shape), jnp.float32)
        outs = {}
        pallas_impl = 'pallas' if on_tpu else 'pallas_interpret'
        runs = ([(i, None) for i in impls] if not args.bwd_impls else
                [(pallas_impl, b) for b in args.bwd_impls])
        baseline_missing = False  # bwd mode: did the first impl fail?
        for run_idx, (impl, bwd) in enumerate(runs):
            if bwd is not None:
                os.environ['KFAC_ATTN_BWD_IMPL'] = bwd
            tag = impl if bwd is None else f'{impl}/bwd={bwd}'

            def loss(q, k, v, impl=impl):
                out = ring_attention(q, k, v, axis_name=None, causal=True,
                                     block_impl=impl)
                return (out.astype(jnp.float32) ** 2).sum()

            fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            try:
                val, grads = fn(q, k, v)  # warms the jit cache
                if bwd is None:
                    # impl mode: forward losses are the agreement basis
                    outs[tag] = float(val)
                elif run_idx == 0:
                    # bwd mode: hold the FIRST impl's grads only; the
                    # second run compares and frees immediately (keeping
                    # both backends' dq/dk/dv would hold 6 full-length
                    # tensors on the host at large L)
                    outs[tag] = [np.asarray(g) for g in grads]
                elif baseline_missing:
                    print(f'  L={L:>7} grad agreement SKIPPED '
                          '(baseline impl failed — timings below are '
                          'unverified)')
                else:
                    prev = next(iter(outs.values()))
                    rels = [float(np.linalg.norm(np.asarray(gb) - ga)
                                  / max(np.linalg.norm(ga), 1e-9))
                            for ga, gb in zip(prev, grads)]
                    print(f'  L={L:>7} grad agreement (dq/dk/dv rel): '
                          + ' '.join(f'{r:.2e}' for r in rels))
                    outs.clear()
                del grads
                # vary q per iteration: identical (program, input)
                # repeats can be served from remote execution caches
                t = timeit(fn, q, k, v, warmup=1, iters=3,
                           vary=lambda i: (q * (1 + 1e-4 * i), k, v))
                print(f'  L={L:>7} {tag:>22}: {t * 1e3:>9.2f} ms '
                      f'({args.batch * L / t / 1e3:>8.1f}K tok/s)')
            except Exception as e:
                if bwd is not None and run_idx == 0 and tag not in outs:
                    # only when the baseline GRADS were never stored — a
                    # later timeit failure still leaves a usable baseline
                    baseline_missing = True
                print(f'  L={L:>7} {tag:>22}: failed '
                      f'({type(e).__name__}: {str(e)[:80]})')
        if not args.bwd_impls and len(outs) == 2:
            a, b = list(outs.values())
            rel = abs(a - b) / max(abs(a), 1e-9)
            print(f'  L={L:>7} loss agreement: rel diff {rel:.2e}')


if __name__ == '__main__':
    main()
