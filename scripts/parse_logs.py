"""Aggregate training-log metrics: throughput, convergence, phase breakdown.

Capability parity with the reference's log aggregation
(reference: scripts/parse_logs.py:1-79 + scripts/reader.py — extract
iteration times / imgs-per-sec / val accuracy from training logs, including
the --exclude-parts subtraction method for phase attribution). Operates on
the log files the example trainers write (one file per RUN via
utils/runlog.py: a config-encoded stem — e.g.
``{dataset}_{model}_kfac{freq}_{variant}[_{F1mc}][_basisN][_warm]_bs{b}_
nd{n}`` — plus a start-time suffix).

Usage:
  python scripts/parse_logs.py logs/*.log            # summary table
  python scripts/parse_logs.py --best logs/*.log     # best val acc per run
"""

import argparse
import os
import re
import sys

# covers all trainer SPEED formats: cifar 'iter time X +- Y s (imgs/sec Z)',
# imagenet 'iter X +- Y s (Z imgs/s)', longcontext '... (tokens/sec Z)'
SPEED_RE = re.compile(
    r'SPEED: iter(?: time)? ([\d.]+) \+- ([\d.]+) s '
    r'\((?:imgs/sec ([\d.]+)|([\d.]+) imgs/s|tokens/sec ([\d.]+))\)')
# One regex per trainer epoch-line format (examples/*.py); each yields
# (epoch, headline_metric, seconds) with higher_is_better per metric.
EPOCH_RES = [
    # cifar10_resnet.py:189 / imagenet_resnet.py:209
    (re.compile(r'epoch (\d+): train_loss ([\d.]+) val_loss ([\d.]+) '
                r'val_acc ([\d.]+) \(([\d.]+)s\)'),
     'val_acc', lambda m: (int(m[1]), float(m[4]), float(m[5])), True),
    # multi30k_transformer.py:261
    (re.compile(r'epoch (\d+): train_loss ([\d.]+) BLEU ([\d.]+) '
                r'\(([\d.]+)s\)'),
     'BLEU', lambda m: (int(m[1]), float(m[3]), float(m[4])), True),
    # squad_bert.py:200
    (re.compile(r'epoch (\d+): loss ([\d.]+) F1 ([\d.]+) EM ([\d.]+) '
                r'\(([\d.]+)s\)'),
     'F1', lambda m: (int(m[1]), float(m[3]), float(m[5])), True),
    # wikitext_rnn.py:139
    (re.compile(r'epoch (\d+): train_ppl ([\d.]+) val_ppl ([\d.]+) '
                r'\(([\d.]+)s\)'),
     'val_ppl', lambda m: (int(m[1]), float(m[3]), float(m[4])), False),
]
ARGS_RE = re.compile(r'args: (\{.*\})')


def parse(path):
    out = {'file': os.path.basename(path), 'epochs': [], 'speed': None,
           'args': None, 'metric': None, 'higher_better': True}
    with open(path) as f:
        for line in f:
            m = ARGS_RE.search(line)
            if m and out['args'] is None:
                out['args'] = m.group(1)
            m = SPEED_RE.search(line)
            if m:
                g = m.groups()
                rate = next(x for x in g[2:] if x is not None)
                unit = 'tok/s' if g[4] is not None else 'imgs/s'
                out['speed'] = (float(g[0]), float(g[1]), float(rate), unit)
            for rex, name, extract, higher in EPOCH_RES:
                m = rex.search(line)
                if m:
                    out['epochs'].append(extract(m))
                    out['metric'] = name
                    out['higher_better'] = higher
                    break
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('logs', nargs='+')
    ap.add_argument('--best', action='store_true',
                    help='print only the best headline metric per run')
    args = ap.parse_args()

    for path in args.logs:
        r = parse(path)
        if r['speed']:
            it, std, ips, unit = r['speed']
            print(f'{r["file"]}: iter {it:.4f}+-{std:.4f}s  '
                  f'{ips:.1f} {unit}')
        if r['epochs']:
            pick = max if r['higher_better'] else min
            best = pick(r['epochs'], key=lambda e: e[1])
            last = r['epochs'][-1]
            mean_t = sum(e[2] for e in r['epochs']) / len(r['epochs'])
            name = r['metric']
            if args.best:
                print(f'{r["file"]}: best {name} {best[1]:.4f} '
                      f'(epoch {best[0]})')
            else:
                print(f'{r["file"]}: {len(r["epochs"])} epochs, '
                      f'best {name} {best[1]:.4f}@{best[0]}, '
                      f'last {last[1]:.4f}, {mean_t:.1f}s/epoch')
        if not r['speed'] and not r['epochs']:
            print(f'{r["file"]}: no metrics found', file=sys.stderr)


if __name__ == '__main__':
    main()
