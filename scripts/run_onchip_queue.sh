#!/bin/bash
# One-shot on-chip validation queue (NOTES.md round-2): run the moment the
# TPU tunnel is up. Sequential (ONE chip job at a time — concurrent jobs
# deadlock on the single chip), timeout-wrapped (jax.devices() hangs when
# the tunnel drops), everything logged under logs/onchip/.
#
# Usage: bash scripts/run_onchip_queue.sh  (repo root; takes hours — nohup it)

set -u
cd "$(dirname "$0")/.."
mkdir -p logs/onchip
TS=$(date +%m%d_%H%M)
L="logs/onchip/queue_${TS}"

run() {  # run <tag> <timeout_s> <cmd...>
  local tag=$1 to=$2; shift 2
  echo "=== [$tag] $(date +%H:%M:%S) timeout=${to}s: $*" | tee -a "$L.summary"
  timeout "$to" "$@" > "$L.$tag.log" 2>&1
  local rc=$?
  echo "=== [$tag] rc=$rc $(date +%H:%M:%S)" | tee -a "$L.summary"
  tail -5 "$L.$tag.log" >> "$L.summary"
  return $rc
}

# 0. probe — abort early if the tunnel is down
run probe 120 python -c "import jax; print(jax.devices())" || {
  echo "tunnel down — aborting queue" | tee -a "$L.summary"; exit 1; }

# 1. flash fwd+bwd sweep incl. 16k/32k (pallas bwd is the default here)
run flash_sweep 3600 python scripts/bench_flash.py \
    --seq-lens 1024 8192 16384 32768

# 2. backward A/B: fused pallas bwd vs blockwise recompute
run flash_bwd_ab 3600 python scripts/bench_flash.py \
    --seq-lens 8192 32768 --bwd-impls pallas recompute

# 3. op micro legs (scripts/bench_ops.py retired into bench.py's
#    BENCH_MICRO mode, ISSUE 19) — decides the KFAC_EIGH_IMPL default
run bench_ops 3600 env BENCH_MICRO=1 python bench.py

# 4. headline bench (fresh compiles can take 30-45 min on a cold cache)
run bench_headline 5400 python bench.py

# 5. full bench: + eigen_dp stock (XLA eigh)
run bench_full_xla 5400 env BENCH_FULL=1 python bench.py

# 6. full bench: eigen_dp with the batched-Jacobi eigh
run bench_full_jacobi 5400 env BENCH_FULL=1 KFAC_EIGH_IMPL=jacobi python bench.py

# 7. experimental paired-rotation jacobi (drop the knob if it loses on MXU)
run bench_full_paired 5400 env BENCH_FULL=1 KFAC_EIGH_IMPL=jacobi \
    KFAC_JACOBI_ROT=paired python bench.py

echo "QUEUE COMPLETE $(date)" | tee -a "$L.summary"
