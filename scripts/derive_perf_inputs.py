"""Derive the analytic perf model's inputs: per-program XLA cost
analysis of the compiled train-step variants (VERDICT r4 #1).

Compiles — on the CPU backend, where compilation needs no chip — the
same cond-free step programs bench.py times on hardware (each
(update_factors, update_inverse, update_basis) combination is its own
jitted program, training.build_train_step), and records XLA's
post-optimization ``cost_analysis()`` flops / bytes-accessed totals.
Dot/conv flop counts are backend-independent; LAPACK custom calls
(eigh / Cholesky / triangular solve on CPU) carry NO flop count, which
is exactly why kfac_pytorch_tpu/perfmodel.py reconstructs the two
decomposition phases from fenced chip measurements (eigh) and analytic
counts (Cholesky) instead of from these totals.

Writes kfac_pytorch_tpu/data/perf_inputs_resnet50_bs32.json (committed;
the perf model and bench.py's `predicted` block read it — regenerate
only when the engine's per-step math changes).

Usage:
  KFAC_PLATFORM=cpu python scripts/derive_perf_inputs.py          # official
  DERIVE_MODEL=resnet20 DERIVE_IMG=32 DERIVE_BATCH=8 ... --out X  # smoke
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

MODEL = os.environ.get('DERIVE_MODEL', 'resnet50')
BATCH = int(os.environ.get('DERIVE_BATCH', 32))
IMG = int(os.environ.get('DERIVE_IMG', 224))
OFFICIAL = (MODEL, BATCH, IMG) == ('resnet50', 32, 224)
DEFAULT_OUT = os.path.join(os.path.dirname(__file__), '..',
                           'kfac_pytorch_tpu', 'data',
                           'perf_inputs_resnet50_bs32.json')


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _cost(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {'flops': float(ca.get('flops', 0.0)),
            'bytes': float(ca.get('bytes accessed', 0.0))}


def analyze(variant, combos):
    """Compile each (uf, ui, ub) combo of one variant's step and return
    {tag: {flops, bytes}} plus the factor plan's bucket table."""
    rng = np.random.RandomState(0)
    n_classes = 1000 if IMG >= 64 else 10
    batch = {'input': jnp.asarray(rng.randn(BATCH, IMG, IMG, 3),
                                  jnp.bfloat16),
             'label': jnp.asarray(rng.randint(0, n_classes, BATCH))}
    model = models.get_model(MODEL, num_classes=n_classes,
                             dtype=jnp.bfloat16)
    tx = training.sgd(0.0125, momentum=0.9, weight_decay=5e-5)
    precond = None
    if variant is not None:
        precond = kfac.KFAC(variant=variant, lr=0.0125, damping=0.002,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=1, axis_name=None,
                            assignment='balanced')
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     extra_mutable=('batch_stats',))
    hyper = training.KFACHyperParams(lr=jnp.float32(0.0125),
                                     damping=jnp.float32(0.002))
    out = {}
    for tag, (uf, ui, ub) in combos.items():
        t0 = time.time()
        if variant is None:
            prog = step.make_variant(False, False)
        else:
            prog = step.make_variant(uf, ui, ub)
        out[tag] = _cost(prog.lower(state, batch, hyper).compile())
        print(f'{tag:>22}: flops={out[tag]["flops"]:.4g} '
              f'bytes={out[tag]["bytes"]:.4g} '
              f'({time.time() - t0:.0f}s compile)', flush=True)
    buckets = None
    if precond is not None:
        buckets = [[int(b.n_rows), int(dim)]
                   for dim, b in sorted(precond.plan.buckets.items())]
    return out, buckets


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--out', default=DEFAULT_OUT)
    args = p.parse_args()
    if not OFFICIAL and os.path.abspath(args.out) == os.path.abspath(
            DEFAULT_OUT):
        p.error('smoke config (DERIVE_* overrides set) would overwrite '
                'the committed official inputs file — pass --out')

    programs = {}
    sgd, _ = analyze(None, {'sgd': (False, False, True)})
    programs.update(sgd)
    inv, buckets = analyze('inverse_dp', {
        'inverse_dp_base': (False, False, True),
        'inverse_dp_factor': (True, False, True),
        'inverse_dp_full': (True, True, True),
    })
    programs.update(inv)
    eig, _ = analyze('eigen_dp', {
        'eigen_dp_base': (False, False, True),
        'eigen_dp_factor': (True, False, True),
        'eigen_dp_full': (True, True, True),
        'eigen_dp_refresh': (True, True, False),
    })
    programs.update(eig)
    ek, _ = analyze('ekfac', {'ekfac_factor': (True, False, True)})
    programs.update(ek)

    doc = {
        'meta': {
            'model': MODEL, 'batch': BATCH, 'img': IMG,
            'official': OFFICIAL,
            'backend': jax.default_backend(),
            'jax_version': jax.__version__,
            'derived_by': 'scripts/derive_perf_inputs.py',
            'note': ('post-optimization compiled cost_analysis totals; '
                     'LAPACK custom calls (eigh/cholesky/trsm) count 0 '
                     'flops on this backend — perfmodel.py reconstructs '
                     'those phases from fenced chip constants and '
                     'analytic counts'),
        },
        'programs': programs,
        'buckets': buckets,
    }
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, 'w') as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print('wrote', args.out)


if __name__ == '__main__':
    main()
