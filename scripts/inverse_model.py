"""Decomposition cost-model data: eigh vs Cholesky-inverse time over factor dims.

Capability parity with the reference's eig-cost probe
(reference: scripts/inverse_model.py:1-42 — `torch.symeig` timing over dims
64..8192 including the real ResNet-50 A/G factor dims) re-designed for the
TPU ops layer: measures both decomposition paths this framework uses
(`ops.sym_eig` for the eigen variants, `ops.psd_inverse` for the inverse
variants) and fits the alpha + beta * d^3 cost model consumed by the
balanced-assignment scheduler (`kfac_pytorch_tpu/parallel/partition.py`).

Usage: python scripts/inverse_model.py [--max-dim 8192] [--csv out.csv]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import fit_linear, force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import ops

# Real ResNet-50 per-layer factor dims (reference: scripts/inverse_model.py:19-20)
RESNET50_A_DIMS = [147, 64, 256, 576, 512, 1024, 1152, 2048, 2304, 4608, 2049]
RESNET50_G_DIMS = [64, 128, 256, 512, 1024, 2048, 1000]


def _spd(rng, dim):
    a = rng.randn(dim, dim).astype(np.float32) / np.sqrt(dim)
    return jnp.asarray(a @ a.T + np.eye(dim, dtype=np.float32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--max-dim', type=int, default=8192)
    p.add_argument('--csv', default=None)
    args = p.parse_args()

    dims = [d for d in (64, 128, 256, 512, 1024, 2048, 4096, 8192)
            if d <= args.max_dim]
    dims = sorted(set(dims + [d for d in RESNET50_A_DIMS + RESNET50_G_DIMS
                              if d <= args.max_dim]))
    rng = np.random.RandomState(0)
    eig_fn = jax.jit(ops.sym_eig)
    inv_fn = jax.jit(ops.psd_inverse)

    rows = []
    print(f'{"dim":>6} {"eigh (ms)":>12} {"chol-inv (ms)":>14} {"ratio":>7}')
    for d in dims:
        x = _spd(rng, d)
        te = timeit(eig_fn, x, iters=5)
        ti = timeit(inv_fn, x, iters=5)
        rows.append((d, te, ti))
        print(f'{d:>6} {te * 1e3:>12.3f} {ti * 1e3:>14.3f} {te / ti:>7.2f}')

    # Fit t = alpha + beta * d^3 (least squares) for each path — the cost
    # model the scheduler's `balanced` assignment uses for layer weights.
    d3 = [r[0] ** 3 for r in rows]
    for name, col in (('eigh', 1), ('chol-inv', 2)):
        alpha, beta = fit_linear(d3, [r[col] for r in rows])
        print(f'{name}: t(d) ~= {alpha * 1e3:.3f} ms + {beta * 1e12:.3f} ps * d^3')

    if args.csv:
        with open(args.csv, 'w') as f:
            f.write('dim,eigh_s,cholinv_s\n')
            for d, te, ti in rows:
                f.write(f'{d},{te:.6f},{ti:.6f}\n')
        print('wrote', args.csv)


if __name__ == '__main__':
    main()
