"""Collective-semantics probes: sub-axis groups, owner-broadcast, barriers.

Capability parity with the reference's comm probes
(reference: scripts/test_allgather.py:19-43 — Horovod process-set allreduce
on even/odd rank subgroups and torch DDP allreduce). The TPU equivalents
this framework relies on:

  1. process-sets      -> mesh *sub-axes*: reshape the device list into a
     2-D mesh and psum over one axis only (the reference's even/odd
     process-set split is the ('group', 'member') factorization here);
  2. per-layer owner broadcast -> owner-computes + all_gather of the
     owner-row table (the masked-psum-friendly form the plan uses);
  3. barrier via dummy allreduce (reference:
     examples/pytorch_wikitext_rnn.py:140-151) -> psum of a scalar.

Run on any mesh; for an 8-way virtual mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python scripts/test_collectives.py
"""

import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kfac_pytorch_tpu.parallel import collectives


def subgroup_allreduce(devices):
    """psum over a sub-axis == process-set allreduce on rank subgroups."""
    n = len(devices)
    if n % 2:
        print('subgroup_allreduce: need even device count, skipping')
        return
    mesh = Mesh(np.array(devices).reshape(2, n // 2), ('parity', 'member'))

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P('parity', 'member'),
                       out_specs=P('parity', 'member'))
    def run(x):
        return jax.lax.psum(x, 'member')  # reduce within parity group only

    x = jax.device_put(
        jnp.arange(n, dtype=jnp.float32).reshape(2, n // 2),
        jax.sharding.NamedSharding(mesh, P('parity', 'member')))
    out = np.asarray(run(x))
    expect = np.tile(np.arange(n, dtype=np.float32).reshape(
        2, n // 2).sum(1, keepdims=True), (1, n // 2))
    assert np.allclose(out, expect), (out, expect)
    print(f'subgroup_allreduce: ok — even group sum {out[0, 0]:.0f}, '
          f'odd group sum {out[1, 0]:.0f}')


def owner_broadcast(devices):
    """Owner computes, everyone receives: the _communicate_pred pattern."""
    n = len(devices)
    mesh = Mesh(np.array(devices), ('kfac',))

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P('kfac'),
                       out_specs=P())
    def run(x):
        idx = jax.lax.axis_index('kfac')
        # each device "owns" its row: computes a result only it knows
        local = x * (idx + 1.0)
        # scatter-to-own-offset + psum: the framework's provably-replicated
        # all-gather (parallel/collectives.py)
        return collectives.all_gather_rows(local, 'kfac')

    x = jax.device_put(
        jnp.ones((n, 3), jnp.float32),
        jax.sharding.NamedSharding(mesh, P('kfac')))
    out = np.asarray(run(x))
    expect = np.tile(np.arange(1, n + 1, dtype=np.float32)[:, None], (1, 3))
    assert np.allclose(out, expect), (out, expect)
    print(f'owner_broadcast: ok — every device holds all {n} owner results')


def barrier(devices):
    """Scalar psum as a barrier (all devices must arrive to complete)."""
    mesh = Mesh(np.array(devices), ('kfac',))

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P('kfac'),
                       out_specs=P())
    def run(x):
        return jax.lax.psum(x.sum(), 'kfac')

    x = jax.device_put(jnp.ones((len(devices),), jnp.float32),
                       jax.sharding.NamedSharding(mesh, P('kfac')))
    assert float(run(x)) == len(devices)
    print('barrier: ok')


def main():
    devices = jax.devices()
    print(f'{len(devices)} devices ({devices[0].platform})')
    subgroup_allreduce(devices)
    owner_broadcast(devices)
    barrier(devices)


if __name__ == '__main__':
    main()
