#!/bin/bash
# Discriminating real-data A/B on the HARDENED digits task (VERDICT r2
# #5 / weak #6): the stock digits-CIFAR task saturates ~.99 val on both
# arms and its 297-image val set quantizes at 0.34%, too coarse to
# separate the warm-kernel legs. This task is 300 train images with 30%
# train-label noise against a 600-image clean val set (0.17%
# quantization, generalization gap forced open), same unmodified
# reference recipe otherwise.
#
# Five 40-epoch legs, sequential, on the virtual CPU mesh (nd=4):
# SGD / cold eigen_dp / warm-NS inverse_dp / basis10 eigen_dp /
# warm-subspace eigen_dp — the same leg set as the round-2 evidence,
# now on a task that can actually rank them. TB scalars land under
# logs/tb_digits_hard/<leg> for plotting.
#
# Usage: nohup bash scripts/run_digits_hard_ab.sh > logs/digits_hard_ab.log 2>&1 &
# AB_SEED=<n> re-runs the whole ladder under a different trainer seed
# (init + shuffle; the dataset/noise split stays fixed) into
# logs/tb_digits_hard_s<n> — error bars across seeds (VERDICT r3 #8).

set -u
cd "$(dirname "$0")/.."
SEED=${AB_SEED:-42}
TB=logs/tb_digits_hard
[ "$SEED" != 42 ] && TB="logs/tb_digits_hard_s$SEED"
mkdir -p "$TB"

python scripts/make_digits_cifar.py /tmp/digits_hard \
    --train-n 300 --val-n 600 --label-noise 0.3

common=(data_dir=/tmp/digits_hard nworkers=4 batch_size=32 epochs=40
        lr_decay="25 35")

leg() {  # leg <name> <env...> -- <extra trainer args...>
  local name=$1; shift
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  echo "=== leg $name seed=$SEED $(date +%H:%M:%S)"
  env "${common[@]}" "${envs[@]}" KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=4 \
      bash train_cifar10.sh --tb-dir "$TB/$name" --seed "$SEED" "$@" \
    || echo "=== leg $name FAILED rc=$?"
}

# AB_LEGS=ekfac runs only the E-KFAC ladder (appended round 4);
# AB_LEGS=trio runs the three-way amortization triangulation
# (cold eigen / plain basis10 / E-KFAC-corrected basis10) for extra
# seeds; default runs the original six legs
if [ "${AB_LEGS:-}" = "trio" ]; then
  leg cold_eigen     kfac=1 kfac_name=eigen_dp --
  leg basis10        kfac=1 kfac_name=eigen_dp basis_freq=10 --
  leg ekfac_b10_d3   kfac=1 kfac_name=ekfac_dp basis_freq=10 \
      -- --damping 0.3
elif [ "${AB_LEGS:-}" != "ekfac" ]; then
  leg sgd            kfac=0 --
  leg cold_eigen     kfac=1 kfac_name=eigen_dp --
  leg cold_chol      kfac=1 kfac_name=inverse_dp --
  leg warm_ns        kfac=1 kfac_name=inverse_dp -- --kfac-warm-start
  leg basis10        kfac=1 kfac_name=eigen_dp basis_freq=10 --
  leg warm_subspace  kfac=1 kfac_name=eigen_dp KFAC_EIGH_IMPL=subspace \
      -- --kfac-warm-start
else
  # E-KFAC on the real conv task: at the recipe damping, at its own
  # larger lambda (the MLP sweep preferred ~10x — its denominators are
  # exact second moments), and amortized-basis at that lambda
  leg ekfac          kfac=1 kfac_name=ekfac_dp --
  leg ekfac_d3       kfac=1 kfac_name=ekfac_dp -- --damping 0.3
  leg ekfac_b10_d3   kfac=1 kfac_name=ekfac_dp basis_freq=10 \
      -- --damping 0.3
fi

echo "=== digits-hard A/B complete $(date)"
python scripts/parse_logs.py logs/cifar10_*digits_hard*.log 2>/dev/null \
  || true
