"""Long-context attention throughput: ring vs Ulysses vs dense replicated.

Benchmark for the sequence-parallel subsystem (no reference counterpart —
SURVEY.md §5.7; this is the framework's beyond-parity capability): tokens/s
of one fwd+bwd attention call at a given global sequence length, sequence
sharded over the available mesh, plus the dense replicated baseline while
it still fits.

Usage:
  KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=8 python scripts/bench_ring.py \
      [--seq-lens 4096 16384] [--heads 8] [--d-head 64] [--impl ring ulysses]
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_pytorch_tpu.parallel.ring_attention import (
    ring_attention, ulysses_attention)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--seq-lens', nargs='+', type=int,
                    default=[4096, 16384])
    ap.add_argument('--batch', type=int, default=1)
    ap.add_argument('--heads', type=int, default=8)
    ap.add_argument('--d-head', type=int, default=64)
    ap.add_argument('--impl', nargs='+',
                    default=['ring', 'ulysses', 'dense'])
    args = ap.parse_args()

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ('seq',))
    spec = P(None, None, 'seq', None)
    print(f'{n} devices ({devices[0].platform}); B={args.batch} '
          f'H={args.heads} D={args.d_head}; fwd+bwd causal attention')

    impls = {
        'ring': functools.partial(ring_attention, axis_name='seq',
                                  causal=True),
        'ulysses': functools.partial(ulysses_attention, axis_name='seq',
                                     causal=True),
        'dense': functools.partial(ring_attention, axis_name=None,
                                   causal=True),
    }

    for L in args.seq_lens:
        rng = np.random.RandomState(0)
        shape = (args.batch, args.heads, L, args.d_head)
        q = jnp.asarray(rng.randn(*shape), jnp.float32)
        k = jnp.asarray(rng.randn(*shape), jnp.float32)
        v = jnp.asarray(rng.randn(*shape), jnp.float32)
        for name in args.impl:
            fn = impls[name]
            if name == 'dense':
                def run(q, k, v, fn=fn):
                    return (fn(q, k, v) ** 2).sum()
                g = jax.jit(jax.grad(run, argnums=(0, 1, 2)))
                qs, ks, vs = q, k, v
            else:
                if name == 'ulysses' and args.heads % n:
                    print(f'  L={L:>7} {name:>8}: skip (heads % devices)')
                    continue
                def local(q, k, v, fn=fn):
                    loss = (fn(q, k, v).astype(jnp.float32) ** 2).sum()
                    return jax.lax.psum(loss, 'seq')
                sharded = jax.shard_map(
                    lambda q, k, v: jax.grad(local, argnums=(0, 1, 2))(
                        q, k, v),
                    mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
                g = jax.jit(sharded)
                sh = NamedSharding(mesh, spec)
                qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
            try:
                t = timeit(g, qs, ks, vs, warmup=1, iters=3,
                           vary=lambda i: (qs * (1 + 1e-4 * i),
                                           ks, vs))
            except Exception as e:  # OOM for dense at long L
                print(f'  L={L:>7} {name:>8}: failed ({type(e).__name__})')
                continue
            toks = args.batch * L / t
            print(f'  L={L:>7} {name:>8}: {t * 1e3:>9.1f} ms '
                  f'({toks / 1e3:>8.1f}K tok/s)')


if __name__ == '__main__':
    main()
