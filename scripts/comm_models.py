"""Collective cost-model fitting: measure psum / all_gather / ppermute
latency vs message size and fit the alpha + beta * size linear model.

Capability parity with the reference's comm-model fitter
(reference: scripts/comm_models.py:8-50 — fits a latency/bandwidth line to
NCCL-broadcast log timings for the performance model behind DP-KFAC's
comm-volume argument). The TPU version measures the collectives this
framework actually issues (`lax.psum` for factor/grad allreduce,
`lax.all_gather` for owner-computed result exchange) over whatever mesh is
available — real ICI on a pod, or a virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)
for model-shape validation.

``--wire-dtype bf16|int8`` measures the collectives at the compressed
wire width (the comm_precision modes of parallel/collectives.py), and
``--analytic MODEL`` prints the closed-form FactorComm / InverseComm /
PredComm payload-byte model per wire dtype (FactorPlan.comm_volume) with
the compression factor each dtype buys — the analytic side of the
HLO-measured ledger in scripts/comm_count.py, and the input the drift
gate (obs/drift.py) scales comm predictions by for compressed runs.

Usage: python scripts/comm_models.py [--sizes-kb 4 64 1024 16384]
           [--csv out] [--wire-dtype fp32|bf16|int8]
           [--analytic resnet20 --variant eigen --ndev 8]
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import fit_linear, force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def analytic_comm_volumes(model_name='resnet20', variant='eigen', ndev=8,
                          num_classes=10, hw=32):
    """{wire dtype: {phase: bytes}} for one full factor+inverse step of
    ``variant`` over ``model_name``'s factor plan — the analytic
    FactorComm/InverseComm/PredComm volume model with its compression
    factor, derived from the SAME plan layout the compiled step uses
    (FactorPlan.comm_volume), so it and the HLO ledger
    (scripts/comm_count.py) describe one object."""
    import jax as _jax
    import jax.numpy as _jnp

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import capture, models
    from kfac_pytorch_tpu.parallel.collectives import WIRE_DTYPES

    model = models.get_model(model_name, num_classes=num_classes)
    x = _jnp.zeros((2, hw, hw, 3), _jnp.float32)
    variables = capture.init(model, _jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    pre = kfac.KFAC(variant=variant, num_devices=ndev, axis_name='batch',
                    assignment='balanced')
    plan = pre.setup(metas)
    return {wd: plan.comm_volume(stats_reduce=pre.stats_reduce,
                                 method=pre.method, comm_precision=wd)
            for wd in WIRE_DTYPES}


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--sizes-kb', nargs='+', type=int,
                   default=[4, 16, 64, 256, 1024, 4096, 16384])
    p.add_argument('--csv', default=None)
    p.add_argument('--wire-dtype', default='fp32',
                   choices=['fp32', 'bf16', 'int8'],
                   help='measure the collectives at this wire width '
                        '(the comm_precision modes)')
    p.add_argument('--analytic', default=None, metavar='MODEL',
                   help='print the closed-form FactorComm/InverseComm/'
                        'PredComm byte model per wire dtype for MODEL '
                        'and exit (no measurement)')
    p.add_argument('--variant', default='eigen',
                   help='K-FAC variant for --analytic')
    p.add_argument('--ndev', type=int, default=8,
                   help='mesh size for --analytic')
    args = p.parse_args()

    if args.analytic:
        vols = analytic_comm_volumes(args.analytic, args.variant,
                                     args.ndev)
        base = vols['fp32']
        print(f'analytic comm volumes: model={args.analytic} '
              f'variant={args.variant} ndev={args.ndev} '
              '(bytes per full factor+inverse step)')
        for wd, phases in vols.items():
            tot, btot = sum(phases.values()), sum(base.values())
            factor = (tot / btot) if btot else 1.0
            line = '  '.join(f'{ph}: {b / 2**20:8.3f} MiB'
                             for ph, b in sorted(phases.items()))
            print(f'{wd:>5}: {line}   total {tot / 2**20:8.3f} MiB '
                  f'(x{factor:.2f} of fp32)')
        return

    devices = jax.devices()
    n = len(devices)
    if n == 1:
        print('single device: collectives are no-ops; run under a pod or a '
              'virtual CPU mesh (--xla_force_host_platform_device_count=8)')
    mesh = Mesh(np.array(devices), ('x',))

    def make(coll):
        @functools.partial(jax.jit)
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=P('x'), out_specs=P('x'))
        def run(x):
            if coll == 'psum':
                return jax.lax.psum(x, 'x')
            if coll == 'all_gather':
                return jax.lax.all_gather(x[0], 'x').mean(0, keepdims=True)
            if coll == 'ppermute':
                return jax.lax.ppermute(
                    x, 'x', [(i, (i + 1) % n) for i in range(n)])
            raise ValueError(coll)
        return run

    rows = {}
    for coll in ('psum', 'all_gather', 'ppermute'):
        fn = make(coll)
        times, sizes_b = [], []
        for kb in args.sizes_kb:
            elems = kb * 1024 // 4
            x = jax.device_put(
                jnp.arange(n * elems, dtype=jnp.float32).reshape(n, elems),
                jax.sharding.NamedSharding(mesh, P('x')))
            t = timeit(fn, x)
            times.append(t)
            sizes_b.append(kb * 1024)
        alpha, beta = fit_linear(sizes_b, times)
        bw = (1.0 / beta / 1e9) if beta > 0 else float('inf')
        rows[coll] = list(zip(sizes_b, times))
        print(f'{coll:>11}: alpha={alpha * 1e6:8.2f} us   '
              f'beta={beta * 1e12:8.3f} ps/B   (~{bw:.2f} GB/s)')
        for sb, t in rows[coll]:
            print(f'    {sb // 1024:>8} KB  {t * 1e6:>10.1f} us')

    if args.csv:
        with open(args.csv, 'w') as f:
            f.write('collective,bytes,seconds\n')
            for coll, data in rows.items():
                for sb, t in data:
                    f.write(f'{coll},{sb},{t:.8f}\n')
        print('wrote', args.csv)


if __name__ == '__main__':
    main()
