"""Collective cost-model fitting: measure psum / all_gather / ppermute
latency vs message size and fit the alpha + beta * size linear model.

Capability parity with the reference's comm-model fitter
(reference: scripts/comm_models.py:8-50 — fits a latency/bandwidth line to
NCCL-broadcast log timings for the performance model behind DP-KFAC's
comm-volume argument). The TPU version measures the collectives this
framework actually issues (`lax.psum` for factor/grad allreduce,
`lax.all_gather` for owner-computed result exchange) over whatever mesh is
available — real ICI on a pod, or a virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)
for model-shape validation.

Usage: python scripts/comm_models.py [--sizes-kb 4 64 1024 16384] [--csv out]
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import fit_linear, force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--sizes-kb', nargs='+', type=int,
                   default=[4, 16, 64, 256, 1024, 4096, 16384])
    p.add_argument('--csv', default=None)
    args = p.parse_args()

    devices = jax.devices()
    n = len(devices)
    if n == 1:
        print('single device: collectives are no-ops; run under a pod or a '
              'virtual CPU mesh (--xla_force_host_platform_device_count=8)')
    mesh = Mesh(np.array(devices), ('x',))

    def make(coll):
        @functools.partial(jax.jit)
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=P('x'), out_specs=P('x'))
        def run(x):
            if coll == 'psum':
                return jax.lax.psum(x, 'x')
            if coll == 'all_gather':
                return jax.lax.all_gather(x[0], 'x').mean(0, keepdims=True)
            if coll == 'ppermute':
                return jax.lax.ppermute(
                    x, 'x', [(i, (i + 1) % n) for i in range(n)])
            raise ValueError(coll)
        return run

    rows = {}
    for coll in ('psum', 'all_gather', 'ppermute'):
        fn = make(coll)
        times, sizes_b = [], []
        for kb in args.sizes_kb:
            elems = kb * 1024 // 4
            x = jax.device_put(
                jnp.arange(n * elems, dtype=jnp.float32).reshape(n, elems),
                jax.sharding.NamedSharding(mesh, P('x')))
            t = timeit(fn, x)
            times.append(t)
            sizes_b.append(kb * 1024)
        alpha, beta = fit_linear(sizes_b, times)
        bw = (1.0 / beta / 1e9) if beta > 0 else float('inf')
        rows[coll] = list(zip(sizes_b, times))
        print(f'{coll:>11}: alpha={alpha * 1e6:8.2f} us   '
              f'beta={beta * 1e12:8.3f} ps/B   (~{bw:.2f} GB/s)')
        for sb, t in rows[coll]:
            print(f'    {sb // 1024:>8} KB  {t * 1e6:>10.1f} us')

    if args.csv:
        with open(args.csv, 'w') as f:
            f.write('collective,bytes,seconds\n')
            for coll, data in rows.items():
                for sb, t in data:
                    f.write(f'{coll},{sb},{t:.8f}\n')
        print('wrote', args.csv)


if __name__ == '__main__':
    main()
