"""Pack sklearn's bundled real handwritten-digits data (1797 8x8 images,
10 classes — genuinely non-synthetic) into the cifar-10-batches-py pickle
format, so the unmodified CIFAR trainer recipe (`--dir`) can produce
real-data convergence evidence in this egress-free environment (VERDICT r1
next #4: CIFAR-10 itself is not obtainable here — documented in NOTES.md).

Images are 4x nearest-upscaled to 32x32 and replicated to 3 channels;
split is a stratified 1500/297 train/test with a fixed seed.

Usage: python scripts/make_digits_cifar.py [outdir=/tmp/digits_cifar]
"""

import os
import pickle
import sys

import numpy as np


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else '/tmp/digits_cifar'
    base = os.path.join(out, 'cifar-10-batches-py')
    os.makedirs(base, exist_ok=True)

    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split
    x, y = load_digits(return_X_y=True)
    # 0..16 -> 0..255 uint8, 8x8 -> 32x32 nearest, gray -> RGB, CHW rows
    img = (x.reshape(-1, 8, 8) * (255.0 / 16.0)).clip(0, 255)
    img = img.repeat(4, axis=1).repeat(4, axis=2).astype(np.uint8)
    img = np.repeat(img[:, None, :, :], 3, axis=1)          # [N, 3, 32, 32]
    flat = img.reshape(len(img), -1)                         # [N, 3072]

    xtr, xte, ytr, yte = train_test_split(
        flat, y, test_size=297, random_state=0, stratify=y)

    chunks = np.array_split(np.arange(len(xtr)), 5)
    for i, idx in enumerate(chunks, start=1):
        with open(os.path.join(base, f'data_batch_{i}'), 'wb') as f:
            pickle.dump({b'data': xtr[idx],
                         b'labels': [int(v) for v in ytr[idx]]}, f)
    with open(os.path.join(base, 'test_batch'), 'wb') as f:
        pickle.dump({b'data': xte,
                     b'labels': [int(v) for v in yte]}, f)
    with open(os.path.join(base, 'batches.meta'), 'wb') as f:
        pickle.dump({b'label_names': [str(i).encode() for i in range(10)]},
                    f)
    print(f'wrote {len(xtr)} train / {len(xte)} test real digit images '
          f'to {base}')


if __name__ == '__main__':
    main()
