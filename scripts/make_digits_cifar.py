"""Pack sklearn's bundled real handwritten-digits data (1797 8x8 images,
10 classes — genuinely non-synthetic) into the cifar-10-batches-py pickle
format, so the unmodified CIFAR trainer recipe (`--dir`) can produce
real-data convergence evidence in this egress-free environment (VERDICT r1
next #4: CIFAR-10 itself is not obtainable here — documented in NOTES.md).

Images are 4x nearest-upscaled to 32x32 and replicated to 3 channels;
default split is a stratified 1500/297 train/test with a fixed seed.

Hardened variant (VERDICT r2 #5: the default task saturates ~.99 and its
297-image val set cannot resolve differences under ~0.34%): --train-n
shrinks the train split, --val-n grows the held-out set (finer accuracy
quantization), --label-noise flips that fraction of TRAIN labels to a
uniformly random wrong class (fixed seed). Val labels are never touched.

Usage: python scripts/make_digits_cifar.py [outdir=/tmp/digits_cifar]
           [--train-n N] [--val-n N] [--label-noise P]
"""

import argparse
import os
import pickle

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('outdir', nargs='?', default='/tmp/digits_cifar')
    ap.add_argument('--train-n', type=int, default=1500,
                    help='train split size (default 1500)')
    ap.add_argument('--val-n', type=int, default=297,
                    help='held-out split size (default 297)')
    ap.add_argument('--label-noise', type=float, default=0.0,
                    help='fraction of TRAIN labels flipped to a random '
                         'wrong class (default 0)')
    args = ap.parse_args()
    base = os.path.join(args.outdir, 'cifar-10-batches-py')
    os.makedirs(base, exist_ok=True)

    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split
    x, y = load_digits(return_X_y=True)
    assert args.train_n + args.val_n <= len(y), (args.train_n, args.val_n)
    # 0..16 -> 0..255 uint8, 8x8 -> 32x32 nearest, gray -> RGB, CHW rows
    img = (x.reshape(-1, 8, 8) * (255.0 / 16.0)).clip(0, 255)
    img = img.repeat(4, axis=1).repeat(4, axis=2).astype(np.uint8)
    img = np.repeat(img[:, None, :, :], 3, axis=1)          # [N, 3, 32, 32]
    flat = img.reshape(len(img), -1)                         # [N, 3072]

    xtr, xte, ytr, yte = train_test_split(
        flat, y, test_size=args.val_n, random_state=0, stratify=y)
    if args.train_n < len(ytr):
        xtr, _, ytr, _ = train_test_split(
            xtr, ytr, train_size=args.train_n, random_state=0,
            stratify=ytr)

    n_noised = 0
    if args.label_noise > 0:
        rng = np.random.RandomState(1)
        flip = rng.rand(len(ytr)) < args.label_noise
        wrong = (ytr + rng.randint(1, 10, size=len(ytr))) % 10
        ytr = np.where(flip, wrong, ytr)
        n_noised = int(flip.sum())

    chunks = np.array_split(np.arange(len(xtr)), 5)
    for i, idx in enumerate(chunks, start=1):
        with open(os.path.join(base, f'data_batch_{i}'), 'wb') as f:
            pickle.dump({b'data': xtr[idx],
                         b'labels': [int(v) for v in ytr[idx]]}, f)
    with open(os.path.join(base, 'test_batch'), 'wb') as f:
        pickle.dump({b'data': xte,
                     b'labels': [int(v) for v in yte]}, f)
    with open(os.path.join(base, 'batches.meta'), 'wb') as f:
        pickle.dump({b'label_names': [str(i).encode() for i in range(10)]},
                    f)
    print(f'wrote {len(xtr)} train ({n_noised} labels noised) / '
          f'{len(xte)} test real digit images to {base}')


if __name__ == '__main__':
    main()
