"""Composed-mesh K-FAC parity gate: the CI driver behind the axis-aware
mesh-plan subsystem (kfac_pytorch_tpu/meshplan).

Each CPU leg runs ONE preconditioned K-FAC step on a composed mesh and
asserts it against the dp-only reference fed the same capture:

* **dp2xtp2** — replicated slice-capture operands, tensor-axis factor
  reduce LIVE in the trace. Gate: every preconditioned grad and every
  factor EMA is BITWISE equal to the dp2 reference (pmean of identical
  f32 values is exact for a power-of-2 world) and tp-invariant across
  model ranks.
* **dp2xep2** — per-expert capture operands. Gate: each expert rank's
  step is BITWISE the dp2 reference run on that expert's capture alone
  (owner-local factors: the zero-FactorComm claim, numerically).

The captures are ORACLE operands — acts/gs/grads enter the shard_map as
explicit inputs, never via in-body autodiff (the legacy shard_map shim
mis-transposes that; see tests/test_tp.py). The preconditioner's own
collectives are forward-only and exact, so the comparison is at lr=0
semantics: preconditioned gradients, no parameter update in the loop.

The ``multichip-*`` legs are STUBS: they record 'needs-chip' unless a
real multi-chip accelerator backend is attached (the on-chip queue runs
them; CI documents the pending surface the same way the comm-ledger job
documents bytes it cannot measure).

Usage:
  KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=8 COMPOSED_PARITY_ASSERT=1 \
      python scripts/composed_parity.py [--leg dp2xtp2 --leg dp2xep2]

Env knobs:
  COMPOSED_PARITY_ASSERT '1' = violations exit nonzero (the CI gate);
                         unset = report-only
  COMPOSED_PARITY_JSON   summary artifact path
                         (default 'composed-parity.json')
"""

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from utils import force_platform  # noqa: E402  (scripts/utils.py)

force_platform()

import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402
import numpy as np                            # noqa: E402

from kfac_pytorch_tpu.capture import LayerMeta       # noqa: E402
from kfac_pytorch_tpu.parallel import mesh as meshlib  # noqa: E402
from kfac_pytorch_tpu.parallel import moe, tp        # noqa: E402
from kfac_pytorch_tpu.preconditioner import KFAC     # noqa: E402

ND, B = 2, 8
CPU_LEGS = ('dp2xtp2', 'dp2xep2')
ALL_LEGS = CPU_LEGS + tuple('multichip-' + leg for leg in CPU_LEGS)


def _dense(name, din, dout):
    return LayerMeta(name=name, path=tuple(name.split('/')), kind='dense',
                     use_bias=True, in_dim=din + 1, out_dim=dout,
                     kernel_shape=(din, dout))


def _metas(leg):
    if 'tp' in leg:
        return ({('l1', 'slice'): _dense('l1/slice', 6, 4),
                 ('l2', 'slice'): _dense('l2/slice', 4, 5)},
                tp.axis_rules(column=('l1',), row=('l2',)))
    return ({('expert', 'w_in'): _dense('expert/w_in', 6, 4),
             ('expert', 'w_out'): _dense('expert/w_out', 4, 5)},
            moe.axis_rules(experts=('expert',)))


def _oracle_inputs(metas, seed, lead=(ND,)):
    rng = np.random.RandomState(seed)

    def arr(*shape):
        return jnp.asarray(rng.randn(*(lead + shape)), jnp.float32)

    acts, gs, grads = {}, {}, {}
    for path, m in metas.items():
        din, dout = m.kernel_shape
        na, ng, nr = acts, gs, grads
        for k in path[:-1]:
            na, ng, nr = (na.setdefault(k, {}), ng.setdefault(k, {}),
                          nr.setdefault(k, {}))
        na[path[-1]] = {'a': arr(B, din)}
        ng[path[-1]] = {'g': arr(B, dout)}
        nr[path[-1]] = {'kernel': arr(din, dout), 'bias': arr(dout)}
    return acts, gs, grads


def _mesh_step(pre, mesh, grads, acts, gs):
    from jax.sharding import PartitionSpec as P
    kspecs = pre.state_pspecs()
    names = tuple(n for n, _ in mesh.shape.items())
    lead = len(names)
    io_spec = P(*names)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(kspecs, io_spec, io_spec, io_spec),
                       out_specs=(io_spec, kspecs))
    def step(kstate, grads, acts, gs):
        sq = lambda t: jax.tree.map(  # noqa: E731
            lambda a: a.reshape(a.shape[lead:]), t)
        g2, st2 = pre.step(kstate, sq(grads), sq(acts), sq(gs))
        exp = lambda t: jax.tree.map(  # noqa: E731
            lambda a: a.reshape((1,) * lead + a.shape), t)
        return exp(g2), st2

    return step(pre.init(), grads, acts, gs)


def _dp_reference(metas, grads, acts, gs):
    pre = KFAC(variant='eigen', lr=0.1, damping=0.01,
               num_devices=ND, axis_name='data')
    pre.setup(metas)
    return _mesh_step(pre, meshlib.make_mesh(ND, axis_name='data'),
                      grads, acts, gs)


def _dup(tree, n):
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[:, None], (a.shape[0], n)
                                   + a.shape[1:]), tree)


def _max_mismatch(got, want, slicer):
    """(bitwise?, max |diff|) over tree leaves after slicing got."""
    worst = 0.0
    bitwise = True
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        a = slicer(np.asarray(a))
        b = np.asarray(b).reshape(a.shape)
        if not np.array_equal(a, b):
            bitwise = False
            worst = max(worst, float(np.abs(a - b).max()))
    return bitwise, worst


def run_cpu_leg(leg):
    metas, rules = _metas(leg)
    pre = KFAC(variant='eigen', lr=0.1, damping=0.01,
               mesh_axes=leg, mesh_rules=rules)
    pre.setup(metas)
    mesh, _ = meshlib.make_composed_mesh(leg)
    res = {'leg': leg, 'status': 'ran', 'checks': {}}

    if 'tp' in leg:
        acts, gs, grads = _oracle_inputs(metas, seed=0)
        got, stc = _mesh_step(pre, mesh, _dup(grads, 2), _dup(acts, 2),
                              _dup(gs, 2))
        gref, stref = _dp_reference(metas, grads, acts, gs)
        tp_inv = all(np.array_equal(np.asarray(a)[:, 0], np.asarray(a)[:, 1])
                     for a in jax.tree_util.tree_leaves(got))
        bit, diff = _max_mismatch(got, gref, lambda a: a[:, 0])
        fbit, fdiff = _max_mismatch(stc.factors, stref.factors, lambda a: a)
        res['checks'] = {'tp_invariant': tp_inv,
                         'grads_bitwise': bit, 'grads_max_diff': diff,
                         'factors_bitwise': fbit,
                         'factors_max_diff': fdiff}
        res['ok'] = tp_inv and bit and fbit
    else:
        per_e = [_oracle_inputs(metas, seed=10 + e) for e in range(2)]
        stack = lambda i: jax.tree.map(  # noqa: E731
            lambda *a: jnp.stack(a, axis=1), *[pe[i] for pe in per_e])
        got, _ = _mesh_step(pre, mesh, stack(2), stack(0), stack(1))
        ok = True
        worst = 0.0
        for e in range(2):
            a_e, g_e, gr_e = per_e[e]
            want, _ = _dp_reference(metas, gr_e, a_e, g_e)
            bit, diff = _max_mismatch(got, want,
                                      lambda a, e=e: a[:, e])
            ok = ok and bit
            worst = max(worst, diff)
        res['checks'] = {'per_expert_bitwise': ok,
                         'max_diff': worst}
        res['ok'] = ok
    return res


def run_multichip_stub(leg):
    """Record the pending on-chip surface; runs only with a real
    multi-chip accelerator attached (the on-chip queue's job)."""
    base = leg.split('-', 1)[1]
    devs = jax.devices()
    if devs[0].platform == 'cpu' or len(devs) < 4:
        return {'leg': leg, 'status': 'needs-chip', 'ok': None,
                'note': f'requires >=4 accelerator devices for {base}; '
                        f'have {len(devs)} x {devs[0].platform}'}
    res = run_cpu_leg(base)
    res['leg'] = leg
    res['note'] = 'ran on-chip'
    return res


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--leg', action='append', choices=ALL_LEGS,
                    help='repeatable; default: all CPU legs + '
                         'multichip stubs')
    args = ap.parse_args(argv)
    legs = tuple(args.leg) if args.leg else ALL_LEGS

    results = []
    for leg in legs:
        res = (run_multichip_stub(leg) if leg.startswith('multichip-')
               else run_cpu_leg(leg))
        results.append(res)
        print(f"{leg:>20}: {res['status']:<10} ok={res['ok']} "
              f"{res.get('checks', res.get('note', ''))}")

    path = os.environ.get('COMPOSED_PARITY_JSON', 'composed-parity.json')
    with open(path, 'w') as f:
        json.dump({'results': results}, f, indent=1, sort_keys=True)
    print(f'wrote {path}')

    failed = [r['leg'] for r in results if r['ok'] is False]
    if failed:
        msg = f'COMPOSED_PARITY: FAILED legs {failed}'
        if os.environ.get('COMPOSED_PARITY_ASSERT') == '1':
            raise SystemExit(msg)
        print(msg)
    elif os.environ.get('COMPOSED_PARITY_ASSERT') == '1':
        ran = [r['leg'] for r in results if r['status'] == 'ran']
        print(f'COMPOSED_PARITY_ASSERT: parity gates passed ({ran})')


if __name__ == '__main__':
    main()
