"""Compiler-level proof of the DP-KFAC communication story: count the
XLA collectives in each variant's COMPILED train step.

The reference's argument for DP-KFAC (kfac_preconditioner_*_dp.py) is
that it deletes the FactorComm (0.300 s) and shrinks the InverseComm
(0.146 s) terms of the 64-GPU MPD ledger (reference
scripts/time_breakdown.py:27). On TPU the equivalent evidence is
hardware-independent: lower the full jitted K-FAC train step over an
8-device mesh and count the all-reduce / all-gather /
collective-permute ops XLA actually emitted. MPD variants ('eigen',
'inverse') must show the factor-reduction collectives; DP variants
('eigen_dp', 'inverse_dp') must show NONE beyond the gradient allreduce
+ preconditioned-output gather; SGD is the gradient-allreduce floor.

Usage: KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=8 python scripts/comm_count.py

Env knobs:
  COMM_COUNT_VARIANTS   space-separated variant specs; a ':bf16'/':int8'
                        suffix compiles the variant with that
                        comm_precision wire dtype (e.g. 'eigen:bf16');
                        a '+pallas' tag compiles it with the fused
                        Pallas capture kernels (e.g. 'eigen+pallas',
                        'eigen+pallas:bf16')
  COMM_COUNT_JSON       write the machine-readable per-variant ledger
                        (ops/bytes per collective kind + per-phase
                        per-dtype breakdown) to this path
  COMM_COUNT_ASSERT     fail unless the SGD floor contains only
                        gradient allreduces, every variant's floor is
                        byte-identical to SGD's, each compressed
                        spec shows >=40% K-FAC collective-byte reduction
                        vs its fp32 counterpart, and each '+pallas'
                        spec's ledger is byte-identical to its unfused
                        counterpart's (the CI smoke gate)
"""

import collections
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
from scripts.utils import force_platform

force_platform()

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

#: one HLO instruction line: `%x = <result type> all-reduce(...)` — the
#: result type carries the payload shape(s) (tuples for variadic ops).
#: The async forms TPU/GPU backends emit for latency hiding
#: (all-reduce-start / -done pairs) are counted via their -start op,
#: whose result type carries the payload; -done carries none.
COLLECTIVE_LINE_RE = re.compile(
    r'= (.*?) ((?:all-reduce|all-gather|collective-permute|reduce-scatter|'
    r'all-to-all)(?:-start)?)\(')
SHAPE_RE = re.compile(r'\b([a-z]\w*)\[([0-9,]*)\]')
OP_NAME_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
DTYPE_BYTES = {'f32': 4, 'bf16': 2, 'f16': 2, 'f64': 8, 's32': 4,
               'u32': 4, 's64': 8, 'u64': 8, 's8': 1, 'u8': 1, 'pred': 1,
               'f8e4m3fn': 1, 'f8e5m2': 1, 'c64': 8, 'c128': 16,
               's16': 2, 'u16': 2}
_WARNED_DTYPES = set()

#: op_name scope substring -> ledger phase (first match wins; the scopes
#: are the engine's jax.named_scope taxonomy, which XLA carries through
#: SPMD partitioning into each collective's metadata). Everything else —
#: the autodiff gradient allreduce, the loss pmean, BN-stat syncs — is
#: the 'grad_or_other' floor that MUST stay byte-identical under any
#: comm_precision (compression never touches the SGD path).
PHASE_OF_SCOPE = (
    # DecompComm first: the shard exchange's gathers run INSIDE the
    # stagger ComputeInverse/CommunicateInverse scopes, and first-match
    # attribution must put them in their own ledger phase
    ('kfac.DecompComm', 'DecompComm'),
    ('kfac.CommunicateFactor', 'FactorComm'),
    ('kfac.CommunicateInverse', 'InverseComm'),
    ('kfac.Precondition', 'PredComm'),
    ('kfac.', 'KfacOther'),
)
FLOOR_PHASE = 'grad_or_other'


def _phase_of(op_name):
    for scope, phase in PHASE_OF_SCOPE:
        if scope in (op_name or ''):
            return phase
    return FLOOR_PHASE


def _payload_bytes_by_dtype(result_type, kind=''):
    """{hlo dtype token: payload bytes} of one collective's result."""
    shapes = SHAPE_RE.findall(result_type)
    if kind.endswith('-start') and result_type.lstrip().startswith('('):
        # an async -start op's tuple result is (operand aliases...,
        # outputs..., context scalars...): counting every element roughly
        # DOUBLES the volume (ADVICE r3). Drop the u32/s32 context
        # scalars, then keep only the output half.
        shapes = [s for s in shapes
                  if not (s[1] == '' and s[0] in ('u32', 's32'))]
        if shapes and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
        elif shapes:
            # the alias/output halves failed to pair 1:1 — the full tuple
            # gets counted, roughly doubling this op's volume (ADVICE r4:
            # flag it so a silently-doubled variant is visible in the
            # ledger instead of quietly inflating it)
            if 'odd-async-tuple' not in _WARNED_DTYPES:
                _WARNED_DTYPES.add('odd-async-tuple')
                print(f'warning: async {kind} result tuple has odd '
                      f'length {len(shapes)} — even alias/output split '
                      'assumption failed; counting the FULL tuple (may '
                      'double this op\'s bytes)', file=sys.stderr)
    out = {}
    for dt, dims in shapes:
        size = DTYPE_BYTES.get(dt)
        if size is None:
            if dt not in _WARNED_DTYPES:
                _WARNED_DTYPES.add(dt)
                print(f'warning: unknown dtype {dt!r} in collective '
                      'result type — assuming 4 bytes', file=sys.stderr)
            size = 4
        n = 1
        for d in dims.split(','):
            if d:
                n *= int(d)
        out[dt] = out.get(dt, 0) + n * size
    return out


def _payload_bytes(result_type, kind=''):
    return sum(_payload_bytes_by_dtype(result_type, kind).values())


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def parse_variant_spec(spec):
    """'eigen' | 'eigen:bf16' | 'eigen+shard:bf16' | 'eigen_dp>inverse'
    -> (variant, comm_precision). '+'-tags ('+shard', '+pallas') stay
    part of the variant name — a compressed tagged spec's fp32
    counterpart is the tagged spec, not the untagged one (different
    programs, different byte model). A '>mode' tag (ISSUE 14) likewise stays part of the
    variant name: the spec lowers the variant AFTER a live
    ``KFAC.replan(comm_mode=mode)`` — the program the autotuner's
    applied comm-mode switch actually runs — and the assert gate pins
    its K-FAC phase bytes against ``FactorPlan.comm_volume`` for the
    switched mode."""
    variant, _, precision = spec.partition(':')
    return variant, (precision or 'fp32')


def parse_capture_tags(variant_tagged):
    """'eigen+pallas' -> ('eigen', shard=False, capture='pallas');
    '+'-tags compose ('eigen+shard+pallas'). Unknown tags fail loudly —
    a typo'd tag must not silently lower the untagged program."""
    base, *tags = variant_tagged.split('+')
    unknown = sorted(set(tags) - {'shard', 'pallas'})
    if unknown:
        raise SystemExit(
            f'unknown variant tag(s) {unknown} in {variant_tagged!r} '
            "(known: '+shard', '+pallas')")
    return (base, 'shard' in tags,
            'pallas' if 'pallas' in tags else None)


def parse_replan_tag(variant):
    """'eigen_dp>inverse' -> ('eigen_dp', 'inverse'); no tag -> (v, None)."""
    base, _, mode = variant.partition('>')
    return base, (mode or None)


def parse_mesh_tag(variant):
    """'eigen@dp2xtp2' -> ('eigen', 'dp2xtp2'); no tag -> (v, None).
    An '@mesh' spec lowers the AXIS-AWARE program: the preconditioner
    step on a composed mesh (meshplan subsystem), with every collective
    attributed to the mesh axis its replica groups actually cross."""
    base, _, spec = variant.partition('@')
    return base, (spec or None)


# -- per-axis attribution (composed meshes) ---------------------------------

REPLICA_GROUPS_RE = re.compile(
    r'replica_groups=(\{\{[0-9, ]*(?:\},\{[0-9, ]*)*\}\}'
    r'|\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)')
SOURCE_TARGET_RE = re.compile(r'source_target_pairs=(\{\{[0-9,{} ]*\}\})')
_IOTA_RE = re.compile(
    r'^\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?$')


def parse_replica_groups(line):
    """Device-id groups of one HLO collective line, or None.

    Handles both serializations XLA emits: the literal
    ``{{0,2},{1,3}}`` list and the iota form ``[2,2]<=[4]`` /
    ``[2,2]<=[2,2]T(1,0)`` (groups = iota over the total, reshaped to
    the source dims, transposed, re-flattened to [n_groups, size]).
    collective-permute's ``source_target_pairs`` parse as 2-element
    groups — a pair crosses whatever axis separates its endpoints.
    """
    m = REPLICA_GROUPS_RE.search(line)
    if m is None:
        m = SOURCE_TARGET_RE.search(line)
        if m is None:
            return None
        body = m.group(1)[2:-2]
        return [tuple(int(x) for x in grp.split(','))
                for grp in body.split('},{') if grp]
    text = m.group(1)
    im = _IOTA_RE.match(text)
    if im:
        out_dims = [int(x) for x in im.group(1).split(',')]
        src_dims = [int(x) for x in im.group(2).split(',')]
        ids = np.arange(int(np.prod(src_dims))).reshape(src_dims)
        if im.group(3):
            ids = ids.transpose([int(x) for x in im.group(3).split(',')])
        ids = ids.reshape(out_dims)
        return [tuple(int(x) for x in row) for row in ids]
    body = text[2:-2]
    return [tuple(int(x) for x in grp.split(','))
            for grp in body.split('},{') if grp]


def axis_of_groups(groups, mesh_shape, axis_names, data_names):
    """Which mesh axis a collective's replica groups cross.

    Device ids are global and row-major over the mesh shape (the
    make_composed_mesh construction), so each member's axis coordinates
    are its unravel. Returns 'data' when every varying coordinate is a
    data/sequence axis (the K-FAC world — multi-axis worlds still count
    as one), the axis name when exactly one non-data axis varies, 'self'
    for degenerate single-member groups, and a '+'-joined label for
    anything mixed (no K-FAC collective should ever produce one).
    """
    varying = set()
    for grp in groups:
        coords = [np.unravel_index(d, mesh_shape) for d in grp]
        for k, name in enumerate(axis_names):
            if len({c[k] for c in coords}) > 1:
                varying.add(name)
    if not varying:
        return 'self'
    if varying <= set(data_names):
        return 'data'
    non_data = sorted(varying - set(data_names))
    if len(non_data) == 1 and len(varying) == 1:
        return non_data[0]
    # crosses a non-data axis AND something else — no K-FAC collective
    # should produce this; the '+' label makes it loud in the ledger
    return '+'.join(sorted(varying))


def collective_ledger(variant, ndev=8, model_name='resnet20', model=None,
                      hw=32, comm_precision='fp32', comm_prefetch=False):
    """Machine-readable collective ledger over the compiled
    (SPMD-partitioned) HLO of one full factor+inverse+precondition+update
    step: op counts and payload bytes per collective kind, plus a
    per-phase (named-scope taxonomy) x per-dtype breakdown — the
    compiler-level proof that a ``comm_precision`` wire dtype shrinks
    FactorComm/InverseComm/PredComm while the gradient-allreduce floor
    stays byte-identical."""
    if len(jax.devices()) < ndev or ndev < 2:
        raise SystemExit(
            f'need a >=2-device mesh (have {len(jax.devices())}, asked '
            f'{ndev}): on one device XLA elides every collective and the '
            'ledger would read all-zero. Run with KFAC_PLATFORM=cpu '
            'KFAC_HOST_DEVICES=8.')
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    rng = np.random.RandomState(0)
    batch = {'input': jnp.asarray(rng.randn(2 * ndev, hw, hw, 3),
                                  jnp.float32),
             'label': jnp.asarray(rng.randint(0, 10, 2 * ndev))}
    if model is None:
        model = models.get_model(model_name, num_classes=10)
    tx = training.sgd(0.1, momentum=0.9)
    # 'eigen+shard': the variant's staggered step with mesh-sharded
    # decomposition (decomp_shard=True implies stagger) — the lowered
    # program is ONE staggered step whose two DecompComm gathers the
    # analytic model prices in closed form. 'variant>mode' (ISSUE 14):
    # lower the program AFTER a live KFAC.replan to the other comm
    # mode — the exact program the autotuner's applied switch runs.
    # '+pallas' (ISSUE 19): the variant with capture_impl='pallas' —
    # fused Pallas capture kernels compute the SAME factor statistics
    # and the SAME wire values, so the program's collective ledger must
    # be byte-identical to the untagged counterpart's (the assert gate
    # below pins exactly that)
    variant_tagged, replan_to = parse_replan_tag(variant)
    base, decomp_shard, capture_impl = parse_capture_tags(variant_tagged)
    precond = None
    if variant != 'sgd':
        precond = kfac.KFAC(variant=base, lr=0.1, damping=0.003,
                            fac_update_freq=1,
                            kfac_update_freq=2 if decomp_shard else 1,
                            num_devices=ndev, axis_name='batch',
                            assignment='balanced',
                            comm_precision=comm_precision,
                            comm_prefetch=comm_prefetch,
                            decomp_shard=decomp_shard,
                            capture_impl=capture_impl)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     axis_name='batch', mesh=mesh,
                                     extra_mutable=('batch_stats',),
                                     donate=False)
    if replan_to is not None:
        # the live switch: rebuild the plan, carry the state (verbatim
        # here — same layout), retrace. What gets lowered below is the
        # SWITCHED program, byte-pinned against comm_volume(comm_mode=)
        state = state.replace(kfac_state=precond.replan(
            jax.device_get(state.kfac_state), comm_mode=replan_to))
    # build the full factor+inverse variant WITHOUT executing a step
    # (AOT lower/compile only — executing first would compile the same
    # program twice) and read the compiled SPMD module's text
    from kfac_pytorch_tpu.preconditioner import KFACHyperParams
    hyper = KFACHyperParams(lr=jnp.float32(0.1), damping=jnp.float32(0.003))
    if decomp_shard:
        jitted = step.make_variant(True, False, stagger_update=True)
    else:
        jitted = step.make_variant(precond is not None,
                                   precond is not None,
                                   prefetch=comm_prefetch)
    txt = jitted.lower(state, batch, hyper).compile().as_text()
    counts = collections.Counter()
    bytes_by_kind = collections.Counter()
    by_phase = {}
    for line in txt.splitlines():
        m = COLLECTIVE_LINE_RE.search(line)
        if not m:
            continue
        result_type, kind = m.groups()
        per_dtype = _payload_bytes_by_dtype(result_type, kind)
        total = sum(per_dtype.values())
        counts[kind] += 1
        bytes_by_kind[kind] += total
        om = OP_NAME_RE.search(line)
        phase = _phase_of(om.group(1) if om else '')
        rec = by_phase.setdefault(
            phase, {'ops': 0, 'bytes': 0, 'by_dtype': {}})
        rec['ops'] += 1
        rec['bytes'] += total
        for dt, b in per_dtype.items():
            rec['by_dtype'][dt] = rec['by_dtype'].get(dt, 0) + b
    led = {
        'variant': variant,
        'comm_precision': comm_precision,
        'comm_prefetch': bool(comm_prefetch),
        'capture_impl': capture_impl,
        'ops': dict(counts),
        'bytes': dict(bytes_by_kind),
        'by_phase': by_phase,
        'total_bytes': int(sum(bytes_by_kind.values())),
    }
    if decomp_shard:
        # the closed-form DecompComm byte price of ONE staggered step
        # under this layout — the COMM_COUNT_ASSERT pin compares the
        # measured by_phase['DecompComm'] bytes against this exactly
        led['decomp_comm_analytic'] = int(precond.plan.comm_volume(
            stats_reduce=precond.stats_reduce, method=precond.method,
            comm_precision=comm_precision,
            decomp_shard=precond.decomp_shard_plan)['DecompComm'])
    if replan_to is not None:
        # the closed-form per-phase byte price of the SWITCHED program
        # (FactorPlan.comm_volume for the replanned mode) — the
        # COMM_COUNT_ASSERT pin compares the measured K-FAC phases
        # against this byte-for-byte (the ISSUE 14 acceptance
        # criterion: the HLO ledger matches the analytic model for the
        # program the applied switch runs)
        led['comm_mode'] = replan_to
        led['comm_mode_analytic'] = {
            k: int(v) for k, v in precond.plan.comm_volume(
                stats_reduce=precond.stats_reduce, method=precond.method,
                comm_precision=comm_precision).items()}
    return led


def collective_counts(variant, ndev=8, model_name='resnet20', model=None,
                      hw=32, comm_precision='fp32'):
    """({op_kind: count}, {op_kind: bytes}) over the compiled
    (SPMD-partitioned) HLO of one full
    factor+inverse+precondition+update step."""
    led = collective_ledger(variant, ndev=ndev, model_name=model_name,
                            model=model, hw=hw,
                            comm_precision=comm_precision)
    return led['ops'], led['bytes']


def composed_ledger(base_variant, mesh_spec, comm_precision='fp32',
                    batch=8):
    """Per-AXIS collective ledger of the axis-aware preconditioner step
    on a composed mesh (meshplan subsystem) — the compiler-level proof
    of the composed-mesh communication story: factor statistics psum
    over the tensor axis exactly the rows the plan marks (column-A /
    row-G), the expert axis carries ZERO factor bytes (owner-local
    DP-KFAC per expert), and the data-axis phases price exactly as the
    base ``FactorPlan.comm_volume``.

    The lowered program feeds ORACLE capture inputs (acts/gs/grads as
    explicit shard_map operands) into ``KFAC.step``: the ledger pins the
    preconditioner's own collectives, independent of how the model
    forward/backward produced the statistics — and independent of the
    legacy-jax in-body autodiff defect tests/helpers.py documents.
    """
    import functools
    from jax.sharding import PartitionSpec as P
    from kfac_pytorch_tpu.capture import LayerMeta
    from kfac_pytorch_tpu.meshplan import axes as axes_mod
    from kfac_pytorch_tpu.parallel import mesh as meshlib
    from kfac_pytorch_tpu.parallel import moe, tp

    axes = axes_mod.parse_mesh_spec(mesh_spec)
    need = axes_mod.total_devices(axes)
    if len(jax.devices()) < need:
        raise SystemExit(
            f'mesh {mesh_spec!r} needs {need} devices (have '
            f'{len(jax.devices())}) — run with KFAC_PLATFORM=cpu '
            f'KFAC_HOST_DEVICES={need}')
    mesh, _ = meshlib.make_composed_mesh(mesh_spec)
    names = tuple(a.name for a in axes)
    shape = axes_mod.mesh_shape(axes)
    data_names = axes_mod.data_axis_names(axes)

    # synthetic capture layer set: column/row tensor slices when the
    # mesh has a tensor axis, an expert-local FFN when it has an expert
    # axis, plus one plain data-world head (unmatched by any rule)
    def dense(name, din, dout):
        return LayerMeta(name=name, path=tuple(name.split('/')),
                         kind='dense', use_bias=True, in_dim=din + 1,
                         out_dim=dout, kernel_shape=(din, dout))
    DIN, DH, DOUT = 24, 32, 16
    metas, rules = {}, []
    if any(a.role == 'tensor' for a in axes):
        metas[('l1', 'slice')] = dense('l1/slice', DIN, DH)
        metas[('l2', 'slice')] = dense('l2/slice', DH, DOUT)
        rules += list(tp.axis_rules(column=('l1',), row=('l2',)))
    if any(a.role == 'expert' for a in axes):
        metas[('expert', 'w_in')] = dense('expert/w_in', DIN, DH)
        metas[('expert', 'w_out')] = dense('expert/w_out', DH, DIN)
        rules += list(moe.axis_rules())
    metas[('head',)] = dense('head', DIN, DOUT)

    pre = kfac.KFAC(variant=base_variant, lr=0.1, damping=0.003,
                    assignment='balanced', comm_precision=comm_precision,
                    mesh_axes=mesh_spec,
                    mesh_rules=tuple(rules) or None)
    pre.setup(metas)
    state0 = pre.init()

    rng = np.random.RandomState(0)

    def leaf(*dims):
        a = jnp.asarray(rng.randn(*dims), jnp.float32)
        return jnp.broadcast_to(a, shape + tuple(dims))

    def insert(tree, path, value):
        d = tree
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = value

    acts, gs, grads = {}, {}, {}
    for path, m in metas.items():
        din = m.in_dim - 1
        insert(acts, path, {'a': leaf(batch, din)})
        insert(gs, path, {'g': leaf(batch, m.out_dim)})
        insert(grads, path, {'kernel': leaf(din, m.out_dim),
                             'bias': leaf(m.out_dim)})

    kspecs = pre.state_pspecs()
    lead = P(*names)
    tree_specs = jax.tree.map(lambda _: lead, (grads, acts, gs))

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(kspecs,) + tree_specs,
                       out_specs=(lead, kspecs))
    def step(kstate, grads, acts, gs):
        sq = lambda t: jax.tree.map(
            lambda a: a.reshape(a.shape[len(shape):]), t)
        new_grads, new_state = pre.step(kstate, sq(grads), sq(acts),
                                        sq(gs))
        exp = lambda t: jax.tree.map(
            lambda a: a.reshape((1,) * len(shape) + a.shape), t)
        return exp(new_grads), new_state

    txt = jax.jit(step).lower(state0, grads, acts, gs) \
                       .compile().as_text()

    counts = collections.Counter()
    bytes_by_kind = collections.Counter()
    by_phase = {}
    by_axis = {}
    total_devices = int(np.prod(shape))
    for line in txt.splitlines():
        m = COLLECTIVE_LINE_RE.search(line)
        if not m:
            continue
        result_type, kind = m.groups()
        per_dtype = _payload_bytes_by_dtype(result_type, kind)
        total = sum(per_dtype.values())
        counts[kind] += 1
        bytes_by_kind[kind] += total
        om = OP_NAME_RE.search(line)
        phase = _phase_of(om.group(1) if om else '')
        rec = by_phase.setdefault(
            phase, {'ops': 0, 'bytes': 0, 'by_dtype': {}})
        rec['ops'] += 1
        rec['bytes'] += total
        for dt, b in per_dtype.items():
            rec['by_dtype'][dt] = rec['by_dtype'].get(dt, 0) + b
        groups = parse_replica_groups(line)
        if groups is None and 'replica_groups={}' in line:
            groups = [tuple(range(total_devices))]
        axis = (axis_of_groups(groups, shape, names, data_names)
                if groups is not None else 'unattributed')
        arec = by_axis.setdefault(axis, {})
        prec = arec.setdefault(phase, {'ops': 0, 'bytes': 0})
        prec['ops'] += 1
        prec['bytes'] += total
    mp = pre.mesh_plan
    analytic = {ax: {k: int(v) for k, v in d.items()}
                for ax, d in mp.comm_volume(
                    stats_reduce=pre.stats_reduce, method=pre.method,
                    comm_precision=comm_precision).items()}
    return {
        'variant': f'{base_variant}@{mesh_spec}',
        'comm_precision': comm_precision,
        'comm_prefetch': False,
        'capture_impl': None,
        'mesh': mesh_spec,
        'mesh_axes': names,
        'data_axes': list(data_names),
        'tensor_axes': list(mp.tensor_axes),
        'expert_axes': list(mp.expert_axes),
        'pipeline_axes': list(mp.pipeline_axes),
        'ops': dict(counts),
        'bytes': dict(bytes_by_kind),
        'by_phase': by_phase,
        'by_axis_phase': by_axis,
        'axis_analytic': analytic,
        'total_bytes': int(sum(bytes_by_kind.values())),
    }


def check_composed(ledgers):
    """The composed-mesh assert gate: for every '@mesh' spec,

    (a) the EXPERT (and pipeline) axes carry ZERO collective bytes — in
        every phase, gradient floor included: the owner-local factor
        trick means nothing the preconditioner lowers may cross them;
    (b) the TENSOR axis carries exactly the analytic FactorComm bytes
        (``MeshFactorPlan.comm_volume``) and NOTHING else;
    (c) the data-axis K-FAC phases price byte-for-byte at the base
        ``FactorPlan.comm_volume`` closed form — the mesh layer changes
        where bytes flow, never how many the data world pays;
    (d) no collective crosses a mixed axis set ('+'-labels) or escapes
        attribution.
    """
    for spec, led in ledgers.items():
        if 'by_axis_phase' not in led:
            continue
        by_axis = led['by_axis_phase']
        analytic = led['axis_analytic']
        for ax in led['expert_axes'] + led['pipeline_axes']:
            got = by_axis.get(ax)
            assert got is None, (
                f'{spec}: collectives cross the {ax} axis: {got} — '
                'expert/pipeline factor state is owner-local; this '
                'axis must carry exactly zero bytes')
        bad = [ax for ax in by_axis
               if '+' in ax or ax == 'unattributed']
        assert not bad, (
            f'{spec}: unattributable/mixed-axis collectives {bad}: '
            f'{ {ax: by_axis[ax] for ax in bad} }')
        for ax in led['tensor_axes']:
            t = dict(by_axis.get(ax, {}))
            want = analytic[ax]['FactorComm']
            got = t.pop('FactorComm', {}).get('bytes', 0)
            assert got == want, (
                f'{spec}: tensor-axis FactorComm {got} B != analytic '
                f'{want} B — the marked-row psum and its byte model '
                'diverged')
            assert not t, (
                f'{spec}: tensor axis {ax} carries non-FactorComm '
                f'collectives {t} — the tensor axis prices exactly one '
                'collective family')
        data = by_axis.get('data', {})
        for phase in ('FactorComm', 'InverseComm', 'PredComm'):
            got = data.get(phase, {}).get('bytes', 0)
            want = analytic['data'][phase]
            assert got == want, (
                f'{spec}: data-axis {phase} {got} B != analytic '
                f'{want} B — the composed program and the base '
                'comm_volume diverged')


def check_floor(ledgers):
    """The smoke-job gate: (a) the 'sgd' ledger contains ONLY
    gradient-path collectives (all-reduce kinds, no gathers, nothing
    attributed to a K-FAC phase), and (b) every compressed spec's
    'grad_or_other' floor phase is byte-identical to its fp32
    counterpart's — a comm_precision wire dtype must never leak into the
    gradient path. Raises AssertionError with the offending record."""
    assert 'sgd' in ledgers, 'check_floor needs an sgd ledger'
    sgd = ledgers['sgd']
    bad = [k for k in sgd['ops']
           if not k.startswith('all-reduce')]
    assert not bad, f'unexpected collectives in the SGD floor: {bad}'
    assert set(sgd['by_phase']) <= {FLOOR_PHASE}, (
        'SGD ledger attributes collectives to a K-FAC phase: '
        f'{sorted(sgd["by_phase"])}')
    for spec, led in ledgers.items():
        variant, precision = parse_variant_spec(spec)
        if precision == 'fp32':
            continue
        # a compressed spec with no fp32 counterpart would make every
        # check below vacuous — fail loudly instead of going green
        # having asserted nothing (e.g. a CI edit that drops the fp32
        # baselines to save time)
        assert variant in ledgers, (
            f'{spec}: no fp32 counterpart {variant!r} in the ledger set '
            '— the floor/compression gates need the baseline; add '
            f'{variant!r} to COMM_COUNT_VARIANTS')
        floor = ledgers[variant]['by_phase'].get(
            FLOOR_PHASE, {}).get('bytes', 0)
        got = led['by_phase'].get(FLOOR_PHASE, {}).get('bytes', 0)
        assert got == floor, (
            f'{spec}: grad/other floor {got} B != {variant} (fp32) '
            f'floor {floor} B — compression (or a regression) touched '
            'the gradient path')
        assert set(led['by_phase'][FLOOR_PHASE]['by_dtype']) == \
            set(ledgers[variant]['by_phase'][FLOOR_PHASE]['by_dtype']), (
            f'{spec}: floor phase dtype set changed vs {variant}')


def main():
    ndev = int(os.environ.get('KFAC_HOST_DEVICES', '8'))
    model_name = os.environ.get('COMM_COUNT_MODEL', 'resnet20')
    print(f'model={model_name} ndev={ndev} (counts from the compiled '
          'SPMD module)')
    # variant specs: 'eigen' (fp32) or 'eigen:bf16' / 'eigen:int8'
    # (compressed factor collectives, parallel/collectives.py wire dtypes)
    specs = tuple(os.environ.get(
        'COMM_COUNT_VARIANTS',
        'sgd eigen inverse eigen_dp inverse_dp '
        'eigen@dp2xtp2 eigen_dp@dp2xtp2 eigen_dp@dp2xep2').split())
    ledgers = {}
    for spec in specs:
        variant, precision = parse_variant_spec(spec)
        mesh_base, mesh_spec = parse_mesh_tag(variant)
        if mesh_spec:
            led = composed_ledger(mesh_base, mesh_spec,
                                  comm_precision=precision)
            ledgers[spec] = led
            per_axis = '; '.join(
                f'{ax}: ' + ', '.join(
                    f'{p} {r["bytes"]}B' for p, r in sorted(d.items()))
                for ax, d in sorted(led['by_axis_phase'].items()))
            print(f'{spec:>17}: ops {led["ops"]}  per-axis {{{per_axis}}}',
                  flush=True)
            continue
        led = collective_ledger(variant, ndev=ndev, model_name=model_name,
                                comm_precision=precision)
        ledgers[spec] = led
        phases = ', '.join(
            f'{p}: {r["bytes"] / 2**20:.2f}'
            for p, r in sorted(led['by_phase'].items()))
        print(f'{spec:>17}: ops {led["ops"]}  MiB by phase {{{phases}}}',
              flush=True)

    kinds = sorted({k for r in ledgers.values() for k in r['ops']})
    print('\nvariant            '
          + '  '.join(f'{k + " (n/MiB)":>26}' for k in kinds))
    for spec, led in ledgers.items():
        print(f'{spec:<17} ' + '  '.join(
            f'{led["ops"].get(k, 0):>16}/{led["bytes"].get(k, 0)/2**20:8.2f}'
            for k in kinds))

    json_path = os.environ.get('COMM_COUNT_JSON')
    if json_path:
        import json
        doc = {'model': model_name, 'ndev': ndev,
               'sgd_floor_bytes': (ledgers['sgd']['total_bytes']
                                   if 'sgd' in ledgers else None),
               'variants': ledgers}
        with open(json_path, 'w') as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        print(f'\nwrote {json_path}')

    # the ledger analog (reference scripts/time_breakdown.py:27): K-FAC
    # comm VOLUME beyond the SGD gradient-allreduce floor
    if 'sgd' in ledgers:
        sgd_bytes = ledgers['sgd']['total_bytes']
        print(f'\nSGD gradient-allreduce floor: {sgd_bytes / 2**20:.2f} '
              'MiB')
        for spec, led in ledgers.items():
            if spec == 'sgd':
                continue
            extra = led['total_bytes'] - sgd_bytes
            print(f'{spec:>17}: +{extra / 2**20:8.2f} MiB K-FAC comm per '
                  'full factor+inverse step')
        # per-spec compression summary against its fp32 counterpart
        for spec, led in ledgers.items():
            variant, precision = parse_variant_spec(spec)
            if precision == 'fp32' or variant not in ledgers:
                continue
            base = ledgers[variant]['total_bytes'] - sgd_bytes
            comp = led['total_bytes'] - sgd_bytes
            if base > 0:
                print(f'{spec:>17}: {100 * (1 - comp / base):.0f}% K-FAC '
                      f'collective-byte reduction vs {variant} (fp32)')
        for spec, led in ledgers.items():
            if 'decomp_comm_analytic' in led:
                meas = led['by_phase'].get('DecompComm', {}).get('bytes', 0)
                print(f'{spec:>17}: DecompComm measured '
                      f'{meas / 2**20:.3f} MiB vs analytic '
                      f'{led["decomp_comm_analytic"] / 2**20:.3f} MiB '
                      '(per staggered step)')
            if 'comm_mode_analytic' in led:
                for phase in ('FactorComm', 'InverseComm', 'PredComm'):
                    meas = led['by_phase'].get(phase, {}).get('bytes', 0)
                    print(f'{spec:>17}: switched-program {phase} measured '
                          f'{meas / 2**20:.3f} MiB vs analytic '
                          f'{led["comm_mode_analytic"][phase] / 2**20:.3f}'
                          ' MiB')
            if led.get('capture_impl') == 'pallas':
                cp = spec.replace('+pallas', '')
                if cp in ledgers:
                    same = led['by_phase'] == ledgers[cp]['by_phase']
                    print(f'{spec:>17}: fused-capture per-phase ledger '
                          f'{"identical to" if same else "DIVERGED from"}'
                          f' {cp}')
        if 'eigen' in ledgers and 'eigen_dp' in ledgers:
            e = ledgers['eigen']['total_bytes'] - sgd_bytes
            edp = ledgers['eigen_dp']['total_bytes'] - sgd_bytes
            if e > 0:
                print(f'\nDP-KFAC deletes {100 * (1 - edp / e):.0f}% of '
                      "MPD eigen's K-FAC comm volume — the FactorComm-"
                      'deletion claim (reference time_breakdown.py:27), '
                      'compiler-verified')

    if os.environ.get('COMM_COUNT_ASSERT'):
        check_floor(ledgers)
        check_composed(ledgers)
        for spec, led in ledgers.items():
            variant, precision = parse_variant_spec(spec)
            if precision == 'fp32':
                continue
            assert variant in ledgers and 'sgd' in ledgers, (
                f'{spec}: the >=40% reduction gate needs both the fp32 '
                f'counterpart {variant!r} and the sgd floor in '
                'COMM_COUNT_VARIANTS')
            sgd_bytes = ledgers['sgd']['total_bytes']
            base = ledgers[variant]['total_bytes'] - sgd_bytes
            comp = led['total_bytes'] - sgd_bytes
            assert base > 0 and comp <= 0.6 * base, (
                f'{spec}: expected >=40% K-FAC collective-byte reduction '
                f'vs {variant}, got {base} -> {comp}')
        # the DecompComm pin: a '+shard' spec's measured shard-exchange
        # bytes must equal FactorPlan.comm_volume's closed-form price
        # EXACTLY, and its gradient floor must be byte-identical to the
        # SGD program's — the shard gathers shrink compute, never touch
        # the gradient path
        for spec, led in ledgers.items():
            analytic = led.get('decomp_comm_analytic')
            if analytic is None:
                continue
            measured = led['by_phase'].get('DecompComm', {}).get('bytes', 0)
            assert measured == analytic, (
                f'{spec}: measured DecompComm {measured} B != analytic '
                f'FactorPlan.comm_volume {analytic} B — the shard '
                'exchange and its byte model diverged')
            # the floor pin compares against the UNSHARDED base
            # variant's program (same preconditioner, same health-guard
            # psum — the SGD program lacks the guard's 4-byte batch_ok
            # reduce, so it is not the right baseline here; the SGD
            # floor itself stays pinned gradient-only by check_floor)
            variant, _ = parse_variant_spec(spec)
            unsharded = variant.partition('+')[0]
            # a shard spec with no unsharded counterpart would make the
            # floor pin vacuously green — fail loudly instead (the same
            # hardening the compressed-spec gates got in PR 8 review)
            assert unsharded in ledgers, (
                f'{spec}: no unsharded counterpart {unsharded!r} in the '
                'ledger set — the gradient-floor pin needs it; add '
                f'{unsharded!r} to COMM_COUNT_VARIANTS')
            base_floor = ledgers[unsharded]['by_phase'].get(
                FLOOR_PHASE, {}).get('bytes', 0)
            got = led['by_phase'].get(FLOOR_PHASE, {}).get('bytes', 0)
            assert got == base_floor, (
                f'{spec}: grad/other floor {got} B != {unsharded} '
                f'floor {base_floor} B — decomp_shard touched the '
                'gradient path')
        # the fused-capture pin (ISSUE 19): a '+pallas' spec lowers the
        # variant with capture_impl='pallas' — the Pallas kernels fuse
        # patch-extract, the factor GEMMs, the EMA and the wire-quantize
        # epilogue into the CAPTURE compute, but emit the same xc/bf16/
        # EF wire values (parallel/collectives.py pins the algebra), so
        # the FactorComm ledger — and every other comm phase — must be
        # byte-identical to the unfused counterpart's. Fusion moves
        # compute, never wire bytes.
        for spec, led in ledgers.items():
            if led.get('capture_impl') != 'pallas':
                continue
            counterpart = spec.replace('+pallas', '')
            assert counterpart in ledgers, (
                f'{spec}: no unfused counterpart {counterpart!r} in the '
                'ledger set — the fused-capture byte pin needs it; add '
                f'{counterpart!r} to COMM_COUNT_VARIANTS')
            other = ledgers[counterpart]
            fc = led['by_phase'].get('FactorComm', {})
            fc0 = other['by_phase'].get('FactorComm', {})
            assert fc == fc0, (
                f'{spec}: FactorComm ledger {fc} != {counterpart} '
                f'FactorComm ledger {fc0} — the fused capture epilogue '
                'changed the wire program (it must only move compute)')
            assert led['by_phase'] == other['by_phase'], (
                f'{spec}: per-phase ledger diverged from {counterpart} '
                'outside FactorComm — the fused capture path leaked '
                'into another comm phase')
            assert led['total_bytes'] == other['total_bytes'], (
                f'{spec}: total {led["total_bytes"]} B != {counterpart} '
                f'total {other["total_bytes"]} B')
        # the comm-mode pin (ISSUE 14): a '>mode' spec's SWITCHED
        # program must price every K-FAC comm phase byte-for-byte at
        # FactorPlan.comm_volume's closed form for the new mode, and
        # its gradient floor must be byte-identical to the UNswitched
        # base variant's program — a replan reroutes factor traffic,
        # never the gradient path
        for spec, led in ledgers.items():
            analytic = led.get('comm_mode_analytic')
            if analytic is None:
                continue
            for phase in ('FactorComm', 'InverseComm', 'PredComm'):
                measured = led['by_phase'].get(phase, {}).get('bytes', 0)
                assert measured == analytic[phase], (
                    f'{spec}: measured {phase} {measured} B != analytic '
                    f'FactorPlan.comm_volume {analytic[phase]} B — the '
                    'replanned program and its byte model diverged')
            base = parse_replan_tag(parse_variant_spec(spec)[0])[0]
            assert base in ledgers, (
                f'{spec}: no unswitched counterpart {base!r} in the '
                'ledger set — the gradient-floor pin needs it; add '
                f'{base!r} to COMM_COUNT_VARIANTS')
            base_floor = ledgers[base]['by_phase'].get(
                FLOOR_PHASE, {}).get('bytes', 0)
            got = led['by_phase'].get(FLOOR_PHASE, {}).get('bytes', 0)
            assert got == base_floor, (
                f'{spec}: grad/other floor {got} B != {base} floor '
                f'{base_floor} B — the comm-mode replan touched the '
                'gradient path')
        print('COMM_COUNT_ASSERT: floor + compression + decomp-shard '
              '+ comm-mode + fused-capture + composed-mesh gates passed')


if __name__ == '__main__':
    main()
