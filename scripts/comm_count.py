"""Compiler-level proof of the DP-KFAC communication story: count the
XLA collectives in each variant's COMPILED train step.

The reference's argument for DP-KFAC (kfac_preconditioner_*_dp.py) is
that it deletes the FactorComm (0.300 s) and shrinks the InverseComm
(0.146 s) terms of the 64-GPU MPD ledger (reference
scripts/time_breakdown.py:27). On TPU the equivalent evidence is
hardware-independent: lower the full jitted K-FAC train step over an
8-device mesh and count the all-reduce / all-gather /
collective-permute ops XLA actually emitted. MPD variants ('eigen',
'inverse') must show the factor-reduction collectives; DP variants
('eigen_dp', 'inverse_dp') must show NONE beyond the gradient allreduce
+ preconditioned-output gather; SGD is the gradient-allreduce floor.

Usage: KFAC_PLATFORM=cpu KFAC_HOST_DEVICES=8 python scripts/comm_count.py
"""

import collections
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
from scripts.utils import force_platform

force_platform()

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

#: one HLO instruction line: `%x = <result type> all-reduce(...)` — the
#: result type carries the payload shape(s) (tuples for variadic ops).
#: The async forms TPU/GPU backends emit for latency hiding
#: (all-reduce-start / -done pairs) are counted via their -start op,
#: whose result type carries the payload; -done carries none.
COLLECTIVE_LINE_RE = re.compile(
    r'= (.*?) ((?:all-reduce|all-gather|collective-permute|reduce-scatter|'
    r'all-to-all)(?:-start)?)\(')
SHAPE_RE = re.compile(r'\b([a-z]\w*)\[([0-9,]*)\]')
DTYPE_BYTES = {'f32': 4, 'bf16': 2, 'f16': 2, 'f64': 8, 's32': 4,
               'u32': 4, 's64': 8, 'u64': 8, 's8': 1, 'u8': 1, 'pred': 1,
               'f8e4m3fn': 1, 'f8e5m2': 1, 'c64': 8, 'c128': 16,
               's16': 2, 'u16': 2}
_WARNED_DTYPES = set()


def _payload_bytes(result_type, kind=''):
    shapes = SHAPE_RE.findall(result_type)
    if kind.endswith('-start') and result_type.lstrip().startswith('('):
        # an async -start op's tuple result is (operand aliases...,
        # outputs..., context scalars...): counting every element roughly
        # DOUBLES the volume (ADVICE r3). Drop the u32/s32 context
        # scalars, then keep only the output half.
        shapes = [s for s in shapes
                  if not (s[1] == '' and s[0] in ('u32', 's32'))]
        if shapes and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
        elif shapes:
            # the alias/output halves failed to pair 1:1 — the full tuple
            # gets counted, roughly doubling this op's volume (ADVICE r4:
            # flag it so a silently-doubled variant is visible in the
            # ledger instead of quietly inflating it)
            if 'odd-async-tuple' not in _WARNED_DTYPES:
                _WARNED_DTYPES.add('odd-async-tuple')
                print(f'warning: async {kind} result tuple has odd '
                      f'length {len(shapes)} — even alias/output split '
                      'assumption failed; counting the FULL tuple (may '
                      'double this op\'s bytes)', file=sys.stderr)
    total = 0
    for dt, dims in shapes:
        size = DTYPE_BYTES.get(dt)
        if size is None:
            if dt not in _WARNED_DTYPES:
                _WARNED_DTYPES.add(dt)
                print(f'warning: unknown dtype {dt!r} in collective '
                      'result type — assuming 4 bytes', file=sys.stderr)
            size = 4
        n = 1
        for d in dims.split(','):
            if d:
                n *= int(d)
        total += n * size
    return total


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def collective_counts(variant, ndev=8, model_name='resnet20', model=None,
                      hw=32):
    """({op_kind: count}, {op_kind: bytes}) over the compiled
    (SPMD-partitioned) HLO of one full
    factor+inverse+precondition+update step."""
    if len(jax.devices()) < ndev or ndev < 2:
        raise SystemExit(
            f'need a >=2-device mesh (have {len(jax.devices())}, asked '
            f'{ndev}): on one device XLA elides every collective and the '
            'ledger would read all-zero. Run with KFAC_PLATFORM=cpu '
            'KFAC_HOST_DEVICES=8.')
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    rng = np.random.RandomState(0)
    batch = {'input': jnp.asarray(rng.randn(2 * ndev, hw, hw, 3),
                                  jnp.float32),
             'label': jnp.asarray(rng.randint(0, 10, 2 * ndev))}
    if model is None:
        model = models.get_model(model_name, num_classes=10)
    tx = training.sgd(0.1, momentum=0.9)
    precond = None
    if variant != 'sgd':
        precond = kfac.KFAC(variant=variant, lr=0.1, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=ndev, axis_name='batch',
                            assignment='balanced')
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     axis_name='batch', mesh=mesh,
                                     extra_mutable=('batch_stats',),
                                     donate=False)
    # build the full factor+inverse variant WITHOUT executing a step
    # (AOT lower/compile only — executing first would compile the same
    # program twice) and read the compiled SPMD module's text
    from kfac_pytorch_tpu.preconditioner import KFACHyperParams
    hyper = KFACHyperParams(lr=jnp.float32(0.1), damping=jnp.float32(0.003))
    jitted = step.make_variant(precond is not None, precond is not None)
    txt = jitted.lower(state, batch, hyper).compile().as_text()
    counts = collections.Counter()
    bytes_by_kind = collections.Counter()
    for result_type, kind in COLLECTIVE_LINE_RE.findall(txt):
        counts[kind] += 1
        bytes_by_kind[kind] += _payload_bytes(result_type, kind)
    return dict(counts), dict(bytes_by_kind)


def main():
    ndev = int(os.environ.get('KFAC_HOST_DEVICES', '8'))
    model_name = os.environ.get('COMM_COUNT_MODEL', 'resnet20')
    print(f'model={model_name} ndev={ndev} (counts from the compiled '
          'SPMD module)')
    variants = tuple(os.environ.get(
        'COMM_COUNT_VARIANTS',
        'sgd eigen inverse eigen_dp inverse_dp').split())
    counts, volumes = {}, {}
    for variant in variants:
        counts[variant], volumes[variant] = collective_counts(
            variant, ndev=ndev, model_name=model_name)
        print(f'{variant:>12}: ops {counts[variant]}  '
              f'MiB {{'
              + ', '.join(f'{k}: {v / 2**20:.2f}'
                          for k, v in volumes[variant].items())
              + '}', flush=True)

    kinds = sorted({k for r in counts.values() for k in r})
    print('\nvariant       '
          + '  '.join(f'{k + " (n/MiB)":>26}' for k in kinds))
    for v in counts:
        print(f'{v:<12} ' + '  '.join(
            f'{counts[v].get(k, 0):>16}/{volumes[v].get(k, 0)/2**20:8.2f}'
            for k in kinds))

    # the ledger analog (reference scripts/time_breakdown.py:27): K-FAC
    # comm VOLUME beyond the SGD gradient-allreduce floor
    if 'sgd' not in volumes:
        return
    sgd_bytes = sum(volumes['sgd'].values())
    print(f'\nSGD gradient-allreduce floor: {sgd_bytes / 2**20:.2f} MiB')
    for variant in variants:
        if variant == 'sgd':
            continue
        extra = sum(volumes[variant].values()) - sgd_bytes
        print(f'{variant:>12}: +{extra / 2**20:8.2f} MiB K-FAC comm per '
              'full factor+inverse step')
    if 'eigen' not in volumes or 'eigen_dp' not in volumes:
        return
    e, edp = (sum(volumes['eigen'].values()) - sgd_bytes,
              sum(volumes['eigen_dp'].values()) - sgd_bytes)
    if e > 0:
        print(f'\nDP-KFAC deletes {100 * (1 - edp / e):.0f}% of MPD '
              "eigen's K-FAC comm volume — the FactorComm-deletion claim "
              '(reference time_breakdown.py:27), compiler-verified')


if __name__ == '__main__':
    main()
