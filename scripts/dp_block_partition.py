"""Load-balanced layer->device scheduling demo on real ResNet-50 factor shapes.

Capability parity with the reference's scheduling research
(reference: scripts/dp_block_partition.py:11-76 — optimal contiguous
bottleneck partition of weighted layers onto P workers, demoed on
ResNet-50 shapes at :89-98, as the smarter alternative to round-robin).

This framework ships all three schedulers as first-class plan policies
(`kfac_pytorch_tpu/parallel/partition.py`; the DP partition and LPT run in
native C++ when `native/libkfac_native.so` is built — see
`kfac_pytorch_tpu/native_lib.py`). This script compares their bottleneck
(makespan) on the real shapes, which is what decides per-step
decomposition latency once the work is sharded over a mesh.

Usage: python scripts/dp_block_partition.py [--devices 4 8 16 64]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np

from kfac_pytorch_tpu.parallel import partition

# ResNet-50 per-layer factor dims: each layer contributes an A (d_a) and a
# G (d_g) decomposition; eigh cost ~ d^3 (reference shapes:
# scripts/inverse_model.py:19-20, scripts/dp_block_partition.py:92-93).
RESNET50_A = [147] + [64, 256, 576, 512] * 4 + [1024, 1152, 2048, 2304] * 8 + \
    [4608, 2048, 2049]
RESNET50_G = [64] + [64, 64, 256, 128] * 4 + [256, 256, 512, 512] * 8 + \
    [512, 2048, 1000]


def makespan(costs, owners, p):
    loads = np.zeros(p)
    for c, o in zip(costs, owners):
        loads[o] += c
    return loads.max(), loads


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--devices', nargs='+', type=int, default=[4, 8, 16, 64])
    args = ap.parse_args()

    costs = np.array([float(d) ** 3 for d in RESNET50_A + RESNET50_G])
    costs /= costs.sum()
    n = len(costs)
    print(f'{n} decomposition tasks (A+G), normalized total cost 1.0\n')
    print(f'{"P":>4} {"round_robin":>12} {"lpt":>12} {"dp_block":>12} '
          f'{"ideal":>8}')
    for p in args.devices:
        rr = partition.round_robin_assign(n, p)
        lpt = partition.balanced_assign(costs, p)
        dp = partition.block_partition(costs, p)
        ms = [makespan(costs, o, p)[0] for o in (rr, lpt, dp)]
        print(f'{p:>4} {ms[0]:>12.4f} {ms[1]:>12.4f} {ms[2]:>12.4f} '
              f'{1.0 / p:>8.4f}')

    print('\nNote: in the stacked-bucket plan (kfac_pytorch_tpu/plan.py) the '
          'assignment decides which mesh row owns each padded slot; the '
          'bottleneck above is the per-step sharded-eigh critical path.')


if __name__ == '__main__':
    main()
