"""On-chip eigh sanity probe: is the timing real, and is the answer right?

scripts/bench_ops.py originally measured batch-4 dim-4608 XLA eigh at
~0.1 ms on the tunnel chip (logs/onchip/queue_0731_0346.bench_ops.log) —
physically impossible (one 4608^3 matmul alone is ~1 ms at v5e peak), so
either ``jax.block_until_ready`` was not fencing execution on this
platform, or eigh was converging to garbage instantly. This probe decides
which: it times the same op three ways (block_until_ready; a forced
device->host transfer, which cannot complete before the computation; and
a host fetch of an on-device scalar reduction) and checks the
decomposition itself (reconstruction ``Q diag(w) Q^T ~= X``,
orthogonality ``Q^T Q ~= I``). First run's verdict (2026-07-31,
logs/onchip/manual_seq.log): decomposition CORRECT, block_until_ready
fence BROKEN (0.15 ms vs multi-second real compute) — which is why all
framework timing now goes through ``utils.profiling.host_fence``.

Methodology notes baked in from review: each timing iteration gets a
distinct input (diagonal jitter) so remote execution caches cannot serve
repeats; the wire-only baseline transfers N distinct precomputed arrays
(np.asarray caches the host value per array, so re-pulling one array is
free after the first fetch); the reduction is fetched to host, not
block_until_ready'd.

Usage: python scripts/check_eigh_onchip.py [--dim 2304] [--batch 4]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import ops


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--dim', type=int, default=2304)
    p.add_argument('--batch', type=int, default=4)
    p.add_argument('--iters', type=int, default=3)
    args = p.parse_args()
    d, b, iters = args.dim, args.batch, args.iters

    rng = np.random.RandomState(0)
    a = rng.randn(b, d, d).astype(np.float32) / np.sqrt(d)
    x = jnp.asarray(a @ a.transpose(0, 2, 1) + np.eye(d, dtype=np.float32))
    eye = jnp.eye(d, dtype=x.dtype) * 1e-4
    xs = [x + (i + 1) * eye for i in range(iters)]  # distinct per iter
    print(f'device: {jax.devices()[0]}  x: {x.shape} {x.dtype}')

    eigh_j = jax.jit(lambda x: ops.sym_eig(x, impl='xla'))
    w, q = jax.block_until_ready(eigh_j(x))  # compile + settle

    # 1) the (broken-on-tunnel) block_until_ready recipe
    t0 = time.perf_counter()
    for xi in xs:
        out = eigh_j(xi)
    jax.block_until_ready(out)
    t_block = (time.perf_counter() - t0) / iters

    # 2) force a full device->host copy of the eigenvectors each iter
    t0 = time.perf_counter()
    for xi in xs:
        _, q2 = eigh_j(xi)
        _ = np.asarray(q2)
    t_xfer = (time.perf_counter() - t0) / iters

    # 3) reduce to one scalar on device, pull only that (host fetch — the
    #    very fence this probe justifies; NOT block_until_ready)
    red = jax.jit(lambda x: sum(jnp.sum(o) for o in eigh_j(x)))
    float(np.asarray(red(x)))  # compile + settle
    t0 = time.perf_counter()
    for xi in xs:
        s = float(np.asarray(red(xi)))
    t_reduce = (time.perf_counter() - t0) / iters

    # transfer-only baseline: N distinct, already-computed same-shape
    # arrays (re-pulling one array is free after its first fetch)
    qs_done = [jax.block_until_ready(eigh_j(xi))[1] for xi in xs]
    time.sleep(1.0)  # let any straggling execution drain
    t0 = time.perf_counter()
    for qd in qs_done:
        _ = np.asarray(qd)
    t_wire = (time.perf_counter() - t0) / iters

    print(f'timing: block_until_ready {t_block * 1e3:9.2f} ms | '
          f'+host transfer {t_xfer * 1e3:9.2f} ms '
          f'(wire-only {t_wire * 1e3:9.2f} ms) | '
          f'scalar-fetch reduce {t_reduce * 1e3:9.2f} ms')

    wn, qn = np.asarray(w), np.asarray(q)
    xn = np.asarray(x)
    recon = qn @ (wn[..., None] * np.swapaxes(qn, -1, -2))
    rec_err = np.max(np.abs(recon - xn)) / np.max(np.abs(xn))
    eye_n = np.eye(d, dtype=np.float32)
    orth_err = max(np.max(np.abs(qi.T @ qi - eye_n)) for qi in qn)
    w_ref = np.linalg.eigvalsh(xn[0])
    w_err = np.max(np.abs(np.sort(wn[0]) - w_ref)) / np.max(np.abs(w_ref))
    print(f'accuracy: recon {rec_err:.2e}  orth {orth_err:.2e}  '
          f'eigvals-vs-numpy {w_err:.2e}')
    ok_acc = rec_err < 1e-3 and orth_err < 1e-3 and w_err < 1e-3
    # a real decomposition at this size cannot beat one matmul's time;
    # judge compute-shaped timings only (reduce, and transfer minus wire).
    # The eigh runs in f32, so the floor uses the f32 MXU peak (half the
    # v5e bf16 peak of 197e12) — using the bf16 figure would make the
    # floor ~2x too low and the verdict more lenient than intended.
    V5E_BF16_PEAK = 197e12
    F32_PEAK = V5E_BF16_PEAK / 2
    floor_ms = 2 * b * d ** 3 / F32_PEAK * 1e3
    compute_ms = max(t_reduce, t_xfer - t_wire) * 1e3
    print(f'one-matmul floor at f32 peak ({F32_PEAK:.0e} FLOP/s): '
          f'{floor_ms:.2f} ms vs measured '
          f'compute {compute_ms:.2f} ms -> timings '
          + ('PLAUSIBLE' if compute_ms > floor_ms else 'IMPLAUSIBLE'))
    print('VERDICT:', 'correct decomposition' if ok_acc
          else 'WRONG RESULTS — do not trust this eigh', '| compute',
          f'~{compute_ms:.2f} ms | block_until_ready fence '
          + ('OK' if t_block >= 0.5 * t_reduce else 'BROKEN'))


if __name__ == '__main__':
    main()
