"""On-chip eigh sanity probe: is the timing real, and is the answer right?

scripts/bench_ops.py measured batch-4 dim-4608 XLA eigh at ~0.1 ms on the
tunnel chip (logs/onchip/queue_0731_0346.bench_ops.log) — physically
impossible (one 4608^3 matmul alone is ~1 ms at v5e peak), so either
``jax.block_until_ready`` is not actually fencing execution on this
platform, or eigh is converging to garbage instantly. This probe decides
which: it times the same op three ways (block_until_ready; a forced
device->host transfer, which cannot complete before the computation; and
a scalar reduction of the outputs) and checks the decomposition itself
(reconstruction ``Q diag(w) Q^T ~= X``, orthogonality ``Q^T Q ~= I``).

Usage: python scripts/check_eigh_onchip.py [--dim 2304] [--batch 4]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import ops


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--dim', type=int, default=2304)
    p.add_argument('--batch', type=int, default=4)
    p.add_argument('--iters', type=int, default=3)
    args = p.parse_args()
    d, b = args.dim, args.batch

    rng = np.random.RandomState(0)
    a = rng.randn(b, d, d).astype(np.float32) / np.sqrt(d)
    x = jnp.asarray(a @ a.transpose(0, 2, 1) + np.eye(d, dtype=np.float32))
    print(f'device: {jax.devices()[0]}  x: {x.shape} {x.dtype}')

    eigh_j = jax.jit(lambda x: ops.sym_eig(x, impl='xla'))
    w, q = jax.block_until_ready(eigh_j(x))  # compile + settle

    # 1) the bench_ops timing recipe
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = eigh_j(x)
    jax.block_until_ready(out)
    t_block = (time.perf_counter() - t0) / args.iters

    # 2) force a full device->host copy of the eigenvectors each iter
    t0 = time.perf_counter()
    for _ in range(args.iters):
        w2, q2 = eigh_j(x)
        _ = np.asarray(q2)
    t_xfer = (time.perf_counter() - t0) / args.iters

    # 3) reduce to one scalar on device, pull only that
    red = jax.jit(lambda x: jax.tree.map(jnp.sum, eigh_j(x)))
    jax.block_until_ready(red(x))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        s = red(x)
    jax.block_until_ready(s)
    t_reduce = (time.perf_counter() - t0) / args.iters

    # transfer-only baseline: pulling an already-computed same-shape array
    # costs the same copy; subtract it so the plausibility verdict sees
    # compute time, not wire time
    q_done = jax.block_until_ready(eigh_j(x))[1]
    t0 = time.perf_counter()
    for _ in range(args.iters):
        _ = np.asarray(q_done)
    t_wire = (time.perf_counter() - t0) / args.iters

    print(f'timing: block_until_ready {t_block * 1e3:9.2f} ms | '
          f'+host transfer {t_xfer * 1e3:9.2f} ms '
          f'(wire-only {t_wire * 1e3:9.2f} ms) | '
          f'scalar reduce {t_reduce * 1e3:9.2f} ms')

    wn, qn = np.asarray(w), np.asarray(q)
    xn = np.asarray(x)
    recon = qn @ (wn[..., None] * np.swapaxes(qn, -1, -2))
    rec_err = np.max(np.abs(recon - xn)) / np.max(np.abs(xn))
    eye = np.eye(d, dtype=np.float32)
    orth_err = max(np.max(np.abs(qi.T @ qi - eye)) for qi in qn)
    w_ref = np.linalg.eigvalsh(xn[0])
    w_err = np.max(np.abs(np.sort(wn[0]) - w_ref)) / np.max(np.abs(w_ref))
    print(f'accuracy: recon {rec_err:.2e}  orth {orth_err:.2e}  '
          f'eigvals-vs-numpy {w_err:.2e}')
    ok_acc = rec_err < 1e-3 and orth_err < 1e-3 and w_err < 1e-3
    # a real decomposition at this size cannot beat one matmul's time;
    # judge compute-shaped timings only (reduce, and transfer minus wire)
    floor_ms = 2 * b * d ** 3 / 197e12 * 1e3
    compute_ms = max(t_reduce, t_xfer - t_wire) * 1e3
    print(f'one-matmul floor at peak: {floor_ms:.2f} ms vs measured '
          f'compute {compute_ms:.2f} ms -> timings '
          + ('PLAUSIBLE' if compute_ms > floor_ms else 'IMPLAUSIBLE'))
    print('VERDICT:', 'correct decomposition' if ok_acc
          else 'WRONG RESULTS — do not trust this eigh', '| slowest timing',
          f'{max(t_block, t_xfer, t_reduce) * 1e3:.2f} ms')


if __name__ == '__main__':
    main()
