"""Op microbenchmarks: eigh / Cholesky-inverse / factor GEMMs vs size.

Port of the reference's offline benches (scripts/bench_ops.py,
scripts/inverse_model.py: eig/gemm timing over dims, replay of real
ResNet-50 factor shapes) for the TPU ops layer. Also A/B-tests the
internal matmul precision of XLA's eigh (QDWH is matmul-bound, so
precision config moves its cost by multiples).

Usage: python scripts/bench_ops.py [--dims 512 1024 2304 4608] [--batch 4]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from scripts.utils import force_platform, timeit
force_platform()

import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import ops

# ResNet-50 per-layer factor dims (reference: scripts/inverse_model.py:19-20)
RESNET50_A_DIMS = [147, 64, 256, 576, 512, 1024, 1152, 2048, 2304, 4608,
                   2049]
RESNET50_G_DIMS = [64, 128, 256, 512, 1024, 2048, 1000]


def spd(rng, batch, dim):
    a = rng.randn(batch, dim, dim).astype(np.float32) / np.sqrt(dim)
    x = a @ a.transpose(0, 2, 1) + np.eye(dim, dtype=np.float32)
    return jnp.asarray(x)


def jitter(x):
    """``vary`` hook for timeit: a per-iteration diagonal shift keeps the
    inputs distinct (same spectrum structure) so remote execution caches
    cannot serve repeats — see scripts/utils.timeit."""
    d = x.shape[-1]
    eye = jnp.eye(d, dtype=x.dtype) * 1e-4
    return lambda i: (x + (i + 1) * eye,)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--dims', nargs='+', type=int,
                   default=[256, 512, 1024, 2304, 4608])
    p.add_argument('--batch', type=int, default=4)
    args = p.parse_args()
    rng = np.random.RandomState(0)

    print(f'device: {jax.devices()[0]}')
    for prec in ['default', 'tensorfloat32', 'highest']:
        with jax.default_matmul_precision(prec):
            # pin the baseline to XLA QDWH so an exported
            # KFAC_EIGH_IMPL=jacobi can't make the A/B compare
            # jacobi against itself
            eigh_j = jax.jit(lambda x: ops.sym_eig(x, impl='xla'))
            inv_j = jax.jit(lambda x: ops.psd_inverse(x))
            for d in args.dims:
                x = spd(rng, args.batch, d)
                te = timeit(eigh_j, x, warmup=1, iters=3, vary=jitter(x))
                ti = timeit(inv_j, x, warmup=1, iters=3, vary=jitter(x))
                print(f'prec={prec:14s} dim={d:5d} batch={args.batch} '
                      f'eigh={te * 1e3:9.1f} ms  chol_inv={ti * 1e3:8.1f} ms')

    # batched matmul-form Jacobi vs XLA QDWH eigh (the K-FAC bucket
    # regime: decompose a whole stacked bucket in one call), cold and
    # warm-started (re-diagonalize a drifted matrix in the prior basis)
    jac = jax.jit(lambda x: ops.jacobi_eigh(x))
    jac_warm = jax.jit(lambda x, b: ops.jacobi_eigh(x, basis=b))
    for d in args.dims:
        if d > 1024:
            continue  # n^4 matmul form cedes large dims to QDWH
        x = spd(rng, args.batch, d)
        tj = timeit(jac, x, warmup=1, iters=3, vary=jitter(x))
        w, q = jac(x)
        werr = float(jnp.max(jnp.abs(
            w - jnp.asarray(np.linalg.eigvalsh(np.asarray(x))))))
        print(f'jacobi_eigh      dim={d:5d} batch={args.batch} '
              f'{tj * 1e3:9.1f} ms  (max |dw| {werr:.2e})')
        drift = spd(rng, args.batch, d)
        xp = 0.6 * x + 0.4 * jnp.asarray(drift) / d
        jw = jitter(xp)
        tw = timeit(jac_warm, xp, q, warmup=1, iters=3,
            vary=lambda i: (*jw(i), q))
        ww, _ = jac_warm(xp, q)
        werr = float(jnp.max(jnp.abs(
            ww - jnp.asarray(np.linalg.eigvalsh(np.asarray(xp))))))
        print(f'jacobi_eigh WARM dim={d:5d} batch={args.batch} '
              f'{tw * 1e3:9.1f} ms  (max |dw| {werr:.2e})')

    # factor GEMM (the ComputeA hot op) at conv-layer shapes
    gemm = jax.jit(lambda a: ops.compute_a_conv(a, (3, 3), (1, 1), (1, 1),
                                                False))
    for c, hw in [(64, 56), (256, 28), (512, 14)]:
        a = jnp.asarray(rng.randn(32, hw, hw, c).astype(np.float32))
        t = timeit(gemm, a, warmup=1, iters=3,
           vary=lambda i: (a + 1e-3 * i,))
        print(f'compute_a_conv c={c:4d} hw={hw:3d} bs=32: {t * 1e3:8.1f} ms')


if __name__ == '__main__':
    main()
