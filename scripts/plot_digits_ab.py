"""Summarize + plot the hardened-digits A/B from its TensorBoard scalars
(VERDICT r2 #5: 'a gap bigger than noise in either direction, logged +
plotted from TB scalars').

Reads every leg directory under the given TB root (written by
scripts/run_digits_hard_ab.sh via --tb-dir) with the framework's native
event-file reader (utils/summary.read_scalars — no tensorboard install),
prints a final/best val-accuracy table with the val-set quantization
noise floor, and writes a val-accuracy-vs-epoch PNG next to the root.

Usage: python scripts/plot_digits_ab.py [logs/tb_digits_hard] [--val-n 600]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from kfac_pytorch_tpu.utils.summary import read_scalars


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('root', nargs='?', default='logs/tb_digits_hard')
    ap.add_argument('--val-n', type=int, default=600,
                    help='held-out set size (quantization = 1/N)')
    ap.add_argument('--out', default=None,
                    help='output png path (default: <root>_ab.png '
                    'derived from the TB dir, so per-seed runs never '
                    'overwrite each other)')
    args = ap.parse_args()

    legs = {}
    for name in sorted(os.listdir(args.root)):
        d = os.path.join(args.root, name)
        if not os.path.isdir(d):
            continue
        series = read_scalars(d)
        if 'val/accuracy' in series:
            legs[name] = series['val/accuracy']
    if not legs:
        raise SystemExit(f'no val/accuracy series under {args.root}')

    quant = 1.0 / args.val_n
    print(f'leg                 final   best    best@ep   '
          f'(val quantization {quant:.4f})')
    for name, acc in legs.items():
        steps, vals = zip(*acc)
        best_i = max(range(len(vals)), key=vals.__getitem__)
        print(f'{name:<18}  {vals[-1]:.4f}  {vals[best_i]:.4f}  '
              f'{steps[best_i]:>5}')
    # pairwise final-accuracy gaps in units of the quantization floor
    names = list(legs)
    print('\npairwise final-acc gaps (in val-quantization units):')
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            gap = legs[a][-1][1] - legs[b][-1][1]
            print(f'  {a} vs {b}: {gap:+.4f} ({gap / quant:+.1f}q)')

    try:
        import matplotlib
        matplotlib.use('Agg')
        import matplotlib.pyplot as plt
    except Exception:
        print('\nmatplotlib unavailable — table only')
        return
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for name, acc in legs.items():
        steps, vals = zip(*acc)
        ax.plot(steps, vals, label=name, linewidth=1.5)
    ax.set_xlabel('epoch')
    ax.set_ylabel('val accuracy')
    ax.set_title('hardened digits (300 train / 30% label noise / '
                 f'{args.val_n} clean val)')
    ax.legend(loc='lower right', fontsize=8)
    ax.grid(alpha=0.3)
    # derive the name from the TB dir so a second-seed summary cannot
    # silently clobber the first's plot (it did once, round 4)
    out = args.out or (os.path.abspath(args.root).rstrip('/')
                       + '_ab.png')
    fig.savefig(out, dpi=120, bbox_inches='tight')
    print(f'\nwrote {out}')


if __name__ == '__main__':
    main()
